"""CLI for the distributed-correctness linter.

``python -m mpit_tpu.analysis [options] [path ...]``

Scans the given files/directories (default: the installed ``mpit_tpu``
package) with rules MPT001–MPT011 — including the cross-module passes
(pickle wire-format drift, protocol-role pairing, wrapper-taint jit
drift) and the explicit-state model check of the extracted PS protocol
(MPT009–011, :mod:`mpit_tpu.analysis.mcheck`), all without importing
anything — subtracts the checked-in baseline, and exits 0 when nothing
new was found. ``--write-baseline`` refreshes the baseline from the
current scan (review the diff — every line you accept is a violation you
are signing off on). ``--fix`` first rewrites the mechanically-fixable
MPT002 sites (known literal tag → ``TAG_*`` name + import) in place,
then lints the result.

Subcommands:

``python -m mpit_tpu.analysis mcheck [--package PATH]``
    Run only the protocol model checks and print per-configuration state
    counts — the exhaustiveness receipt behind MPT009–011, plus the
    ``fleet-route`` configuration (MPT019: no routed request lost under
    a single replica kill) when the serving-fleet roles are in the scan.

``python -m mpit_tpu.analysis conform <obs-dir> [--package PATH]``
    Replay an observability run (``obs_rank*.jsonl`` + ``faults*.jsonl``)
    against the extracted protocol; report TC201–TC203 violations.

``python -m mpit_tpu.analysis threads [--package PATH] [--owner X]``
    Print the whole-program concurrency model behind MPT013–015: every
    thread root, the state shared across roots, and the lockset each
    root holds at each access. ``--owner PServer`` narrows to one
    class/module's state (shared or not); ``--json`` emits the
    machine-readable form the threading-model doc is generated from.

``python -m mpit_tpu.analysis schema [--json|--check|--update-lock]``
    Print the inferred per-tag payload-schema table behind MPT016–018
    (sender construction shapes vs receiver consumption patterns, plus
    the snapshot write/read key sets). ``--check`` diffs it against the
    checked-in ``wire-schema.lock.json`` and exits 1 on undeclared
    drift; ``--update-lock`` regenerates the lock — protocol-shape
    changes are *declared*, never silent.

``python -m mpit_tpu.analysis numerics [--package PATH] [--json]``
    Print the whole-program precision-dataflow model behind MPT020–022:
    every quantize site with its error-feedback verdict (paired /
    ef-off[reason] / escapes / unpaired), dequantize mode/scale
    provenance, reductions whose operand is quantized codes, and the
    per-wire-tag precision ledger vs the lockfile's precision column.

``python -m mpit_tpu.analysis fuzz [--corpus PATH] [--examples N]``
    The differential codec fuzz gate: seeded strategies over the
    structural payload grammar drive encode→decode roundtrips,
    framed-vs-pickle differential equality, and frame mutations that
    must always land on WireDecodeError — never a wrong value.
    ``--corpus`` additionally replays the checked-in regression corpus;
    ``--regen-corpus`` rebuilds it deterministically.

Exit codes (every mode, regardless of output format): 0 clean (vs
baseline), 1 new findings / violations, 2 usage or input error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from mpit_tpu.analysis import findings as findings_mod
from mpit_tpu.analysis import lint


def _default_scan_path() -> str:
    return str(Path(__file__).resolve().parent.parent)


def _load_project(package: str):
    modules = []
    for ap, rel in lint.collect_files([package]):
        ctx = lint.load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    return lint.Project(modules=modules, config=lint.Config())


def _main_mcheck(argv) -> int:
    from mpit_tpu.analysis import mcheck, protocol

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis mcheck",
        description="Exhaustively model-check the extracted PS protocol "
        "under single-fault schedules (MPT009-MPT011).",
    )
    parser.add_argument(
        "--package",
        default=_default_scan_path(),
        help="package to extract the protocol from (default: mpit_tpu)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    if not Path(args.package).exists():
        print(f"error: no such path: {args.package}", file=sys.stderr)
        return 2
    project = _load_project(args.package)
    sem = protocol.extract_semantics(project)
    if sem is None or not sem.has_fault_machinery:
        print(
            "error: no fault-tolerant protocol pair extracted from "
            f"{args.package} (need marked roles with attempt ids or a "
            "dedup window)",
            file=sys.stderr,
        )
        return 2
    results = mcheck.check_all(mcheck.from_protocol(sem))
    fsem = protocol.extract_fleet_semantics(project)
    if fsem is not None:
        results.append(
            mcheck.check_fleet(mcheck.fleet_from_protocol(fsem))
        )
    bad = False
    if args.json:
        print(json.dumps([
            {
                "config": r.config.label,
                "states": r.states,
                "fault_points": r.fault_points,
                "violations": r.violations,
                "truncated": r.truncated,
            }
            for r in results
        ], indent=2))
        bad = any(not r.ok for r in results)
    else:
        for r in results:
            status = "ok" if r.ok else "FAIL"
            print(
                f"{status}: {r.config.label}: {r.states} states, "
                f"{r.fault_points} single-fault schedules explored"
            )
            for rule in sorted(r.violations):
                print(f"  {rule}: {r.violations[rule]}")
            if r.truncated:
                print("  truncated: state bound hit, result inconclusive")
            bad = bad or not r.ok
    return 1 if bad else 0


def _main_conform(argv) -> int:
    from mpit_tpu.analysis import conformance

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis conform",
        description="Replay obs journals against the extracted protocol "
        "(TC201-TC204).",
    )
    parser.add_argument(
        "obs_dir",
        nargs="+",
        help="directories with obs_rank*.jsonl journals (and, for "
        "chaos runs, faults*.jsonl), or single journal files; several "
        "run dirs share one protocol extraction, each is audited "
        "separately",
    )
    parser.add_argument(
        "--faults",
        metavar="PATH",
        help="chaos fault log (default: faults*.jsonl inside obs_dir)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="ignore membership.jsonl: audit an elastic run's journals "
        "with no churned-rank licensing (TC201/TC202 relaxations off)",
    )
    parser.add_argument(
        "--package",
        default=_default_scan_path(),
        help="package to extract the protocol from (default: mpit_tpu)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    for d in args.obs_dir:
        if not Path(d).exists():
            print(f"error: no such path: {d}", file=sys.stderr)
            return 2
    if not Path(args.package).exists():
        print(f"error: no such path: {args.package}", file=sys.stderr)
        return 2
    project = _load_project(args.package)  # extracted once, audited per dir
    docs = []
    bad = False
    for d in args.obs_dir:
        report = conformance.check_conformance(
            d, project, faults_path=args.faults,
            elastic=False if args.strict else None,
        )
        if not report.journals:
            print(
                f"error: no obs_rank*.jsonl journals under {d}",
                file=sys.stderr,
            )
            return 2
        bad = bad or bool(report.violations)
        if args.json:
            docs.append({
                "obs_dir": d,
                "journals": [str(p) for p in report.journals],
                "events": report.events,
                "sends": report.sends,
                "recvs": report.recvs,
                "faults": report.faults,
                "churned": report.churned,
                "truncated": report.truncated,
                "violations": [
                    {"rule": v.rule, "detail": v.detail}
                    for v in report.violations
                ],
            })
        else:
            for v in report.violations:
                print(v)
            where = f" [{d}]" if len(args.obs_dir) > 1 else ""
            elastic_note = (
                f", elastic churn on rank(s) {report.churned}"
                if report.churned else ""
            )
            trunc_note = (
                f", truncated journal(s) on rank(s) {report.truncated}"
                if report.truncated else ""
            )
            print(
                f"{len(report.violations)} violation(s) in "
                f"{len(report.journals)} journal(s): {report.sends} "
                f"send(s), {report.recvs} recv(s), "
                f"{report.faults} fault record(s)"
                + elastic_note + trunc_note + where
            )
    if args.json:
        # single-dir invocations keep the original flat document shape
        print(json.dumps(docs[0] if len(docs) == 1 else docs, indent=2))
    return 1 if bad else 0


def _fmt_locksets(locksets) -> str:
    return " | ".join(
        "{" + ", ".join(ls) + "}" if ls else "{}" for ls in locksets
    )


def _main_threads(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis threads",
        description="Dump the whole-program concurrency model "
        "(thread roots, cross-root shared state, per-access locksets) "
        "that rules MPT013-MPT015 consume.",
    )
    parser.add_argument(
        "--package",
        default=_default_scan_path(),
        help="package to analyze (default: mpit_tpu)",
    )
    parser.add_argument(
        "--owner",
        metavar="SUFFIX",
        help="list ALL tracked state of one owner (class or module "
        "dotted-name suffix, e.g. PServer), shared across roots or not",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    if not Path(args.package).exists():
        print(f"error: no such path: {args.package}", file=sys.stderr)
        return 2
    model = _load_project(args.package).threads

    def _root_block(per_root):
        out = {}
        for root, e in sorted(per_root.items()):
            out[root] = {
                "reads": e["reads"],
                "writes": e["writes"],
                "locksets": sorted(
                    sorted(l.short() for l in ls) for ls in e["locksets"]
                ),
            }
        return out

    if args.owner:
        states = model.owner_state(args.owner)
        doc = {
            "owner": args.owner,
            "state": [
                {
                    "state": s.label(),
                    "kind": s.kind,
                    "shared": len(per_root) >= 2,
                    "roots": _root_block(per_root),
                }
                for s, per_root in sorted(
                    states.items(), key=lambda kv: kv[0].label()
                )
            ],
        }
        if args.json:
            print(json.dumps(doc, indent=2))
        else:
            for ent in doc["state"]:
                mark = "shared" if ent["shared"] else "single-root"
                print(f"{ent['state']}  [{mark}]")
                for root, e in ent["roots"].items():
                    print(
                        f"    {root}: {e['reads']}r/{e['writes']}w  "
                        f"{_fmt_locksets(e['locksets'])}"
                    )
        return 0

    doc = model.to_json()
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0
    print(f"{len(doc['roots'])} thread root(s):")
    for r in doc["roots"]:
        note = "" if r["resolved"] else "  [unresolved target]"
        print(f"  {r['name']}  <- {r['target']} @ {r['spawned_at']}{note}")
    print(f"\n{len(doc['shared_state'])} cross-root shared state(s):")
    for ent in doc["shared_state"]:
        print(f"  {ent['state']}")
        for root, e in ent["roots"].items():
            print(
                f"    {root}: {e['reads']}r/{e['writes']}w  "
                f"{_fmt_locksets(e['locksets'])}"
            )
    print(f"\n{len(doc['lock_edges'])} lock-order edge(s):")
    for edge in doc["lock_edges"]:
        print(f"  {edge}")
    return 0


def _default_lock_path(package: str):
    root = lint.find_repo_root(Path(package))
    if root is None:
        return None
    from mpit_tpu.analysis import schema as schema_mod

    return root / schema_mod.SCHEMA_LOCK_FILENAME


def _schema_drift_lines(locked: dict, inferred: dict) -> list:
    """Human-readable per-tag drift between the lock and the scan."""
    out = []
    ltags = locked.get("tags", {})
    itags = inferred.get("tags", {})
    for key in sorted(set(ltags) | set(itags), key=int):
        lt, it = ltags.get(key), itags.get(key)
        name = (it or lt or {}).get("name") or f"tag {key}"
        if lt is None:
            out.append(f"  {name} ({key}): not in lock (new tag)")
            continue
        if it is None:
            out.append(f"  {name} ({key}): in lock but no longer inferred")
            continue
        for side in ("sender", "receiver", "precision"):
            if lt.get(side) != it.get(side):
                out.append(
                    f"  {name} ({key}) {side}: lock {lt.get(side)} != "
                    f"inferred {it.get(side)}"
                )
    lsnap = locked.get("snapshot", {})
    isnap = inferred.get("snapshot", {})
    for side in ("writes", "reads"):
        if lsnap.get(side) != isnap.get(side):
            out.append(
                f"  snapshot {side}: lock {lsnap.get(side)} != "
                f"inferred {isnap.get(side)}"
            )
    if locked.get("version") != inferred.get("version"):
        out.append(
            f"  lock version {locked.get('version')!r} != "
            f"{inferred.get('version')!r}"
        )
    return out


def _main_schema(argv) -> int:
    from mpit_tpu.analysis import schema as schema_mod

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis schema",
        description="Infer the per-tag wire payload schemas (MPT016-018"
        " model) and diff them against wire-schema.lock.json.",
    )
    parser.add_argument(
        "--package",
        default=_default_scan_path(),
        help="package to analyze (default: mpit_tpu)",
    )
    parser.add_argument(
        "--lock",
        metavar="PATH",
        help="lock file (default: wire-schema.lock.json at the repo root)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit 1 when the inferred schema drifts from the lock",
    )
    parser.add_argument(
        "--update-lock",
        action="store_true",
        help="regenerate the lock from the current scan (declaring the "
        "protocol change) and exit 0",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    if not Path(args.package).exists():
        print(f"error: no such path: {args.package}", file=sys.stderr)
        return 2
    model = _load_project(args.package).schema
    doc = model.to_json()
    lock_path = (
        Path(args.lock) if args.lock else _default_lock_path(args.package)
    )

    if args.update_lock:
        if lock_path is None:
            print(
                "error: no lock path (pass --lock or run inside the repo)",
                file=sys.stderr,
            )
            return 2
        with open(lock_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(doc['tags'])} tag schema(s) to {lock_path}")
        return 0

    if args.check:
        if lock_path is None or not lock_path.exists():
            print(
                f"error: no schema lock at {lock_path} — generate it "
                "with --update-lock",
                file=sys.stderr,
            )
            return 2
        with open(lock_path) as f:
            locked = json.load(f)
        drift = _schema_drift_lines(locked, doc)
        if not drift:
            print(
                f"wire schema: {len(doc['tags'])} tag(s) match "
                f"{lock_path.name}"
            )
            return 0
        print(f"wire schema drifted from {lock_path}:")
        for line in drift:
            print(line)
        print(
            "declare the protocol change with: python -m "
            "mpit_tpu.analysis schema --update-lock"
        )
        return 1

    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    for key in sorted(doc["tags"], key=int):
        ent = doc["tags"][key]
        name = ent["name"] or f"tag {key}"
        print(f"{name} ({key})")
        print(f"  sender:   {', '.join(ent['sender']) or '(none seen)'}")
        print(f"  receiver: {', '.join(ent['receiver']) or '(none seen)'}")
        if ent.get("precision"):
            print(f"  precision: {', '.join(ent['precision'])}")
    snap = doc["snapshot"]
    print(
        f"snapshot: writes {snap['writes'] or '(none)'} / "
        f"reads {snap['reads'] or '(none)'}"
    )
    return 0


def _main_numerics(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis numerics",
        description="Dump the whole-program precision-dataflow model "
        "(quantize sites with error-feedback verdicts, dequantize "
        "provenance, code-operand reductions, per-tag wire precision) "
        "that rules MPT020-MPT022 consume.",
    )
    parser.add_argument(
        "--package",
        default=_default_scan_path(),
        help="package to analyze (default: mpit_tpu)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)
    if not Path(args.package).exists():
        print(f"error: no such path: {args.package}", file=sys.stderr)
        return 2
    doc = _load_project(args.package).numerics.to_json()
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0
    print(f"{len(doc['quant_sites'])} quantize site(s):")
    for q in doc["quant_sites"]:
        reason = (
            f"  ({q['ef_off_reason']})" if "ef_off_reason" in q else ""
        )
        print(
            f"  {q['site']}  {q['func']}[{q['mode']}]  "
            f"ef={q['ef']}{reason}  <{q['symbol']}>"
        )
    print(f"\n{len(doc['dequant_sites'])} dequantize site(s):")
    for d in doc["dequant_sites"]:
        print(
            f"  {d['site']}  {d['func']}[declared={d['declared_mode']} "
            f"codes={d['codes_mode']} scale={d['scale']}]  "
            f"<{d['symbol']}>"
        )
    print(
        f"\n{len(doc['reduce_sites'])} code-operand reduction(s):"
        + ("" if doc["reduce_sites"] else "  (clean)")
    )
    for r in doc["reduce_sites"]:
        print(f"  {r['site']}  {r['func']}({r['operand']})  <{r['symbol']}>")
    if doc["tags"]:
        print(f"\n{len(doc['tags'])} wire tag(s) with a precision pin:")
        for key in sorted(doc["tags"], key=int):
            ent = doc["tags"][key]
            mark = "" if ent["inferred"] == ent["locked"] else "  DRIFT"
            print(
                f"  {ent['name']} ({key}): inferred {ent['inferred']} / "
                f"locked {ent['locked']}{mark}"
            )
    return 0


def _main_fuzz(argv) -> int:
    from mpit_tpu.transport import fuzz

    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis fuzz",
        description="Differential codec fuzz gate: roundtrip + "
        "framed-vs-pickle equality over the structural payload grammar, "
        "plus frame mutations that must always land on WireDecodeError.",
    )
    parser.add_argument(
        "--seed", type=int, default=0, help="PRNG seed (default: 0)"
    )
    parser.add_argument(
        "--examples",
        type=int,
        default=10000,
        help="generated examples (default: 10000)",
    )
    parser.add_argument(
        "--corpus",
        metavar="PATH",
        help="also replay this regression corpus (jsonl)",
    )
    parser.add_argument(
        "--regen-corpus",
        metavar="PATH",
        help="deterministically rebuild the regression corpus and exit",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)

    if args.regen_corpus:
        n = fuzz.write_corpus(args.regen_corpus, seed=args.seed)
        print(f"wrote {n} corpus entries to {args.regen_corpus}")
        return 0

    report = fuzz.run_fuzz(seed=args.seed, examples=args.examples)
    if args.corpus:
        if not Path(args.corpus).exists():
            print(
                f"error: no such corpus: {args.corpus}", file=sys.stderr
            )
            return 2
        report.merge(fuzz.replay_corpus(args.corpus))
    if args.json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        print(report.summary())
        for line in report.failures[:10]:
            print(f"  FAIL {line}")
    return 1 if report.failures else 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # subcommands keep the plain lint invocation's flag surface intact
    # (paths are positional, so a literal first arg dispatches cleanly)
    if argv and argv[0] == "mcheck":
        return _main_mcheck(argv[1:])
    if argv and argv[0] == "conform":
        return _main_conform(argv[1:])
    if argv and argv[0] == "threads":
        return _main_threads(argv[1:])
    if argv and argv[0] == "schema":
        return _main_schema(argv[1:])
    if argv and argv[0] == "numerics":
        return _main_numerics(argv[1:])
    if argv and argv[0] == "fuzz":
        return _main_fuzz(argv[1:])
    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis",
        description="Distributed-correctness linter (rules MPT001-MPT008).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the mpit_tpu package)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    parser.add_argument(
        "--json",
        dest="format",
        action="store_const",
        const="json",
        help="shorthand for --format json (same 0/1/2 exit gate — the "
        "baseline gate never depends on the output format)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        help="baseline file (default: analysis-baseline.json at the repo "
        "root, or $MPIT_ANALYSIS_BASELINE)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring any baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--fix",
        action="store_true",
        help="rewrite fixable MPT002 sites (known literal tag -> TAG_* "
        "constant + import) in place before linting",
    )
    parser.add_argument(
        "--only",
        metavar="RULES",
        help="run only these comma-separated rule ids (e.g. "
        "--only MPT013,MPT014) — rule modules owning none of them are "
        "skipped entirely, so one rule iterates without the full pass",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from mpit_tpu.analysis.rules import RULE_DOCS

        for rule_id in sorted(RULE_DOCS):
            slug, doc = RULE_DOCS[rule_id]
            print(f"{rule_id}  {slug:<26} {doc}")
        return 0

    paths = args.paths or [_default_scan_path()]
    for p in paths:
        if not Path(p).exists():
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    if args.fix:
        from mpit_tpu.analysis import fixes

        had_error = False
        for r in fixes.fix_paths(paths):
            if r.error:
                had_error = True
                print(f"fix: {r.path}: {r.error}", file=sys.stderr)
                continue
            detail = f"rewrote {r.replaced} literal tag site(s)"
            if r.imported:
                detail += f", imported {', '.join(r.imported)}"
            if r.skipped:
                detail += f", left {r.skipped} suppressed site(s)"
            print(f"fix: {r.path}: {detail}")
        if had_error:
            return 2

    config = None
    if args.only:
        only = [r.strip() for r in args.only.split(",") if r.strip()]
        from mpit_tpu.analysis.rules import RULE_DOCS

        unknown = [r for r in only if r not in RULE_DOCS]
        if unknown:
            print(
                f"error: unknown rule id(s): {', '.join(unknown)} "
                "(see --list-rules)",
                file=sys.stderr,
            )
            return 2
        config = lint.Config(only_rules=only)

    all_findings = lint.run_lint(paths, config)

    baseline_path = None
    if not args.no_baseline:
        baseline_path = (
            Path(args.baseline)
            if args.baseline
            else lint.default_baseline_path(paths[0])
        )

    if args.write_baseline:
        if baseline_path is None:
            print(
                "error: no baseline path (pass --baseline or run inside "
                "the repo)",
                file=sys.stderr,
            )
            return 2
        findings_mod.write_baseline(baseline_path, all_findings)
        print(
            f"wrote {len(all_findings)} finding(s) to {baseline_path}"
        )
        return 0

    baseline = None
    if baseline_path is not None:
        try:
            baseline = findings_mod.load_baseline(baseline_path)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    new = findings_mod.new_findings(all_findings, baseline)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in new],
                    "total_scanned": len(all_findings),
                    "baselined": len(all_findings) - len(new),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.format())
        suffix = (
            f" ({len(all_findings) - len(new)} baselined)"
            if baseline
            else ""
        )
        print(f"{len(new)} new finding(s){suffix}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
