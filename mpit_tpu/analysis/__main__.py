"""CLI for the distributed-correctness linter.

``python -m mpit_tpu.analysis [options] [path ...]``

Scans the given files/directories (default: the installed ``mpit_tpu``
package) with rules MPT001–MPT008 — including the cross-module passes
(pickle wire-format drift, protocol-role pairing, wrapper-taint jit
drift), which resolve imports and constants across the whole scan set
without importing anything — subtracts the checked-in baseline, and exits
0 when nothing new was found. ``--write-baseline`` refreshes the baseline
from the current scan (review the diff — every line you accept is a
violation you are signing off on). ``--fix`` first rewrites the
mechanically-fixable MPT002 sites (known literal tag → ``TAG_*`` name +
import) in place, then lints the result.

Exit codes: 0 clean (vs baseline), 1 new findings, 2 usage/baseline error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from mpit_tpu.analysis import findings as findings_mod
from mpit_tpu.analysis import lint


def _default_scan_path() -> str:
    return str(Path(__file__).resolve().parent.parent)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpit_tpu.analysis",
        description="Distributed-correctness linter (rules MPT001-MPT008).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/directories to scan (default: the mpit_tpu package)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    parser.add_argument(
        "--baseline",
        metavar="PATH",
        help="baseline file (default: analysis-baseline.json at the repo "
        "root, or $MPIT_ANALYSIS_BASELINE)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring any baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current findings as the new baseline and exit 0",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--fix",
        action="store_true",
        help="rewrite fixable MPT002 sites (known literal tag -> TAG_* "
        "constant + import) in place before linting",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from mpit_tpu.analysis.rules import RULE_DOCS

        for rule_id in sorted(RULE_DOCS):
            slug, doc = RULE_DOCS[rule_id]
            print(f"{rule_id}  {slug:<26} {doc}")
        return 0

    paths = args.paths or [_default_scan_path()]
    for p in paths:
        if not Path(p).exists():
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    if args.fix:
        from mpit_tpu.analysis import fixes

        had_error = False
        for r in fixes.fix_paths(paths):
            if r.error:
                had_error = True
                print(f"fix: {r.path}: {r.error}", file=sys.stderr)
                continue
            detail = f"rewrote {r.replaced} literal tag site(s)"
            if r.imported:
                detail += f", imported {', '.join(r.imported)}"
            if r.skipped:
                detail += f", left {r.skipped} suppressed site(s)"
            print(f"fix: {r.path}: {detail}")
        if had_error:
            return 2

    all_findings = lint.run_lint(paths)

    baseline_path = None
    if not args.no_baseline:
        baseline_path = (
            Path(args.baseline)
            if args.baseline
            else lint.default_baseline_path(paths[0])
        )

    if args.write_baseline:
        if baseline_path is None:
            print(
                "error: no baseline path (pass --baseline or run inside "
                "the repo)",
                file=sys.stderr,
            )
            return 2
        findings_mod.write_baseline(baseline_path, all_findings)
        print(
            f"wrote {len(all_findings)} finding(s) to {baseline_path}"
        )
        return 0

    baseline = None
    if baseline_path is not None:
        try:
            baseline = findings_mod.load_baseline(baseline_path)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    new = findings_mod.new_findings(all_findings, baseline)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in new],
                    "total_scanned": len(all_findings),
                    "baselined": len(all_findings) - len(new),
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f.format())
        suffix = (
            f" ({len(all_findings) - len(new)} baselined)"
            if baseline
            else ""
        )
        print(f"{len(new)} new finding(s){suffix}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
