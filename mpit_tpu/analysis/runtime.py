"""Runtime lock-order and tag-concurrency checker for the transport layer.

Opt-in instrumentation (zero overhead when off — the transports call
:func:`make_lock` at construction and :func:`active_checker` per recv, both
of which short-circuit on the module-level ``_ACTIVE`` being None):

- **RT101 lock-order cycles.** Every :class:`_TrackedLock` acquisition
  records, per thread, the set of locks already held and adds *order edges*
  ``held -> acquiring`` to a global directed graph. A cycle in that graph is
  a potential deadlock EVEN IF the runs that built the two halves of the
  cycle never overlapped in time — which is exactly why a graph beats
  timeout-based detection: the inversion is caught on a clean single-run
  test, not on the unlucky production schedule.
- **RT102 concurrent tag reuse.** :class:`~mpit_tpu.transport.inproc.Broker`
  registers every blocking ``get`` (recv) as a *waiter* keyed by
  ``(broker, dst, src, tag)``. Two waiters on the same mailbox whose
  filters can match the same message — same concrete tag, sources equal or
  either a wildcard, different threads — mean two protocol roles are
  racing for one tag: whichever recv matches first steals the other role's
  message. (Wildcard-tag waiters are exempt: ``recv(ANY_TAG)`` is the
  single-threaded dispatcher pattern, e.g. the pserver loop.)

Usage::

    from mpit_tpu.analysis import runtime
    with runtime.checking() as checker:
        ...construct transports / brokers and run traffic...
    assert not checker.findings

Locks created BEFORE the checker was enabled stay untracked (they were
handed out as plain ``threading.Lock``): enable the checker first, then
construct the transports under test.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import threading
from typing import Iterator, Optional

ANY = -1  # mirrors transport.ANY_SOURCE/ANY_TAG without importing transport


@dataclasses.dataclass(frozen=True)
class RuntimeFinding:
    rule: str  # "RT101" | "RT102"
    message: str

    def format(self) -> str:
        return f"{self.rule}: {self.message}"


@dataclasses.dataclass(frozen=True)
class _Waiter:
    token: int
    thread: int
    thread_name: str
    broker: int  # id() of the broker — scoping is per broker
    dst: int
    src: int
    tag: int

    def overlaps(self, other: "_Waiter") -> bool:
        if self.broker != other.broker or self.dst != other.dst:
            return False
        if self.thread == other.thread:
            return False  # one role draining sequentially
        if self.tag == ANY or other.tag == ANY:
            return False  # wildcard dispatcher pattern
        if self.tag != other.tag:
            return False
        return (
            self.src == other.src or self.src == ANY or other.src == ANY
        )


class RuntimeChecker:
    """Collects RT101/RT102 findings; thread-safe; activate via
    :func:`checking` (or :func:`enable`/:func:`disable` for long-lived
    diagnostics sessions)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.findings: list = []
        # lock-order graph over lock INSTANCES (ids) — names alias freely
        # (every per-dst lock shares one name) so identity is the node
        self._edges: dict = {}  # id -> set(id)
        self._names: dict = {}  # id -> name
        self._reported_edges: set = set()
        self._held = threading.local()
        self._waiters: dict = {}  # token -> _Waiter
        self._token_counter = itertools.count(1)
        self._reported_tags: set = set()

    # -- lock-order graph -------------------------------------------------

    def _held_stack(self) -> list:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def on_acquire(self, lock: "_TrackedLock") -> None:
        """Called BEFORE the underlying acquire blocks, so a deadlock in
        progress still records the edge that explains it."""
        stack = self._held_stack()
        me = id(lock)
        with self._mu:
            self._names[me] = lock.name
            for held in stack:
                if held == me:
                    continue  # reentrant misuse; RT101 is not that check
                self._add_edge(held, me)
        stack.append(me)

    def on_release(self, lock: "_TrackedLock") -> None:
        stack = self._held_stack()
        me = id(lock)
        # remove the most recent occurrence; out-of-order release is legal
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == me:
                del stack[i]
                break

    def _add_edge(self, a: int, b: int) -> None:
        """a held while acquiring b. Caller holds self._mu."""
        if b in self._edges.setdefault(a, set()):
            return
        self._edges[a].add(b)
        path = self._find_path(b, a)
        if path is not None:
            key = frozenset(path)
            if key not in self._reported_edges:
                self._reported_edges.add(key)
                names = " -> ".join(
                    self._names.get(n, f"lock@{n:#x}") for n in path + [b]
                )
                self.findings.append(
                    RuntimeFinding(
                        "RT101",
                        "lock-order cycle (potential deadlock): "
                        f"{names} — two threads acquire these locks in "
                        "opposite orders",
                    )
                )

    def _find_path(self, start: int, goal: int) -> Optional[list]:
        """DFS path start..goal in the edge graph, else None."""
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    # -- tag concurrency --------------------------------------------------

    def on_recv_enter(
        self, broker, dst: int, src: int, tag: int
    ) -> int:
        """Register a blocking recv; returns a token for
        :meth:`on_recv_exit`. Emits RT102 when an already-active waiter on
        the same mailbox can match the same messages."""
        th = threading.current_thread()
        waiter = _Waiter(
            token=next(self._token_counter),
            thread=th.ident or 0,
            thread_name=th.name,
            broker=id(broker),
            dst=dst,
            src=src,
            tag=tag,
        )
        with self._mu:
            for other in self._waiters.values():
                if waiter.overlaps(other):
                    key = (waiter.broker, dst, tag)
                    if key not in self._reported_tags:
                        self._reported_tags.add(key)
                        self.findings.append(
                            RuntimeFinding(
                                "RT102",
                                f"tag {tag} on rank {dst} is being "
                                "received concurrently by threads "
                                f"{other.thread_name!r} (src filter "
                                f"{other.src}) and "
                                f"{waiter.thread_name!r} (src filter "
                                f"{waiter.src}) — two protocol roles "
                                "share one tag; whichever matches first "
                                "steals the other's message",
                            )
                        )
            self._waiters[waiter.token] = waiter
        return waiter.token

    def on_recv_exit(self, token: int) -> None:
        with self._mu:
            self._waiters.pop(token, None)


class _TrackedLock:
    """threading.Lock wrapper reporting acquisition order to a checker.

    Bound to the checker active at CREATION time, so a checker torn down
    mid-flight (the ``checking()`` block exited while a transport thread
    still runs) keeps receiving events instead of the thread crashing."""

    def __init__(self, name: str, checker: RuntimeChecker):
        self._lock = threading.Lock()
        self.name = name
        self._checker = checker

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._checker.on_acquire(self)
        got = self._lock.acquire(blocking, timeout)
        if not got:
            self._checker.on_release(self)
        return got

    def release(self) -> None:
        self._lock.release()
        self._checker.on_release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


_ACTIVE: Optional[RuntimeChecker] = None


def active_checker() -> Optional[RuntimeChecker]:
    return _ACTIVE


def make_lock(name: str):
    """The transport lock factory: a plain ``threading.Lock`` normally, a
    tracked lock while a checker is active. ``name`` is the diagnostic
    role label (instances may share it; identity drives the graph)."""
    checker = _ACTIVE
    if checker is None:
        return threading.Lock()
    return _TrackedLock(name, checker)


def enable(checker: Optional[RuntimeChecker] = None) -> RuntimeChecker:
    global _ACTIVE
    _ACTIVE = checker or RuntimeChecker()
    return _ACTIVE


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def checking() -> Iterator[RuntimeChecker]:
    """Enable a fresh checker for the block; disables on exit (the checker
    object and its findings stay readable afterwards)."""
    checker = enable()
    try:
        yield checker
    finally:
        disable()
