"""Runtime lock-order and tag-concurrency checker for the transport layer.

Opt-in instrumentation (zero overhead when off — the transports call
:func:`make_lock` at construction and :func:`active_checker` per recv, both
of which short-circuit on the module-level ``_ACTIVE`` being None):

- **RT101 lock-order cycles.** Every :class:`_TrackedLock` acquisition
  records, per thread, the set of locks already held and adds *order edges*
  ``held -> acquiring`` to a global directed graph. A cycle in that graph is
  a potential deadlock EVEN IF the runs that built the two halves of the
  cycle never overlapped in time — which is exactly why a graph beats
  timeout-based detection: the inversion is caught on a clean single-run
  test, not on the unlucky production schedule.
- **RT102 concurrent tag reuse.** :class:`~mpit_tpu.transport.inproc.Broker`
  registers every blocking ``get`` (recv) as a *waiter* keyed by
  ``(broker, dst, src, tag)``. Two waiters on the same mailbox whose
  filters can match the same message — same concrete tag, sources equal or
  either a wildcard, different threads — mean two protocol roles are
  racing for one tag: whichever recv matches first steals the other role's
  message. (Wildcard-tag waiters are exempt: ``recv(ANY_TAG)`` is the
  single-threaded dispatcher pattern, e.g. the pserver loop.)
- **RT103 happens-before races** (opt-in on top of a checker: ``race=True``
  or ``MPIT_RT_RACE=1``). Every tracked lock/condition carries a vector
  clock: release publishes the holder's clock into the lock and advances
  the holder; acquire joins the lock's clock into the acquirer. Annotated
  shared structures (PServer center/version/counts, Broker mailboxes —
  via :func:`note`) record per-variable last-write/read epochs; an access
  not ordered after the previous conflicting access by that clock algebra
  is a data race REGARDLESS of how the schedule happened to interleave —
  the dynamic complement of static MPT013, reported with both stacks.
- **RT104 numerics sanitizer** (opt-in: ``numerics=True`` or
  ``MPIT_RT_NUMERICS=1``). The dynamic complement of static MPT020-022:
  the quant kernels' host faces (:mod:`mpit_tpu.quant` peeks for an armed
  checker, never the other way round), the PServer apply path, and the
  sync/PS error-feedback state report into :func:`note_numeric_array` /
  ``on_quantize`` / :func:`note_residual_norm`. Checks: NaN/Inf reaching
  a quantize or the server center, int8 absmax overflow (non-finite or
  non-positive scale), the zero-absmax pin (scale 1, codes all zero —
  quant.py's hardened contract), and EF-residual norm boundedness — the
  same per-round norm the dynamics plane journals as ``elastic`` must
  stay finite and not grow without bound. One finding per call site,
  with the caller's stack.

Usage::

    from mpit_tpu.analysis import runtime
    with runtime.checking() as checker:
        ...construct transports / brokers and run traffic...
    assert not checker.findings

Locks created BEFORE the checker was enabled stay untracked (they were
handed out as plain ``threading.Lock``): enable the checker first, then
construct the transports under test.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import os
import sys
import threading
import traceback
from typing import Iterator, Optional

ANY = -1  # mirrors transport.ANY_SOURCE/ANY_TAG without importing transport


@dataclasses.dataclass(frozen=True)
class RuntimeFinding:
    rule: str  # "RT101" | "RT102" | "RT103" | "RT104"
    message: str

    def format(self) -> str:
        return f"{self.rule}: {self.message}"


@dataclasses.dataclass(frozen=True)
class _Waiter:
    token: int
    thread: int
    thread_name: str
    broker: int  # id() of the broker — scoping is per broker
    dst: int
    src: int
    tag: int

    def overlaps(self, other: "_Waiter") -> bool:
        if self.broker != other.broker or self.dst != other.dst:
            return False
        if self.thread == other.thread:
            return False  # one role draining sequentially
        if self.tag == ANY or other.tag == ANY:
            return False  # wildcard dispatcher pattern
        if self.tag != other.tag:
            return False
        return (
            self.src == other.src or self.src == ANY or other.src == ANY
        )


class RuntimeChecker:
    """Collects RT101/RT102 findings; thread-safe; activate via
    :func:`checking` (or :func:`enable`/:func:`disable` for long-lived
    diagnostics sessions)."""

    def __init__(self, race: bool = False, numerics: bool = False):
        self._mu = threading.Lock()
        self.findings: list = []
        # lock-order graph over lock INSTANCES (ids) — names alias freely
        # (every per-dst lock shares one name) so identity is the node
        self._edges: dict = {}  # id -> set(id)
        self._names: dict = {}  # id -> name
        self._reported_edges: set = set()
        self._held = threading.local()
        self._waiters: dict = {}  # token -> _Waiter
        self._token_counter = itertools.count(1)
        self._reported_tags: set = set()
        # -- RT103 vector-clock state (race=True only) --
        self.race = race
        self._race_tids = threading.local()  # small stable per-thread ids
        self._race_tid_counter = itertools.count(1)
        self._clocks: dict = {}  # tid -> {tid: clk}
        self._vars: dict = {}  # key -> {"w": epoch|None, "r": {tid: epoch}}
        self._reported_races: set = set()
        # -- RT104 numerics state (numerics=True only) --
        self.numerics = numerics
        self._reported_numerics: set = set()  # (caller file:line, kind)
        self._resid_norms: dict = {}  # key -> [observed finite norms]

    # -- lock-order graph -------------------------------------------------

    def _held_stack(self) -> list:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def on_acquire(self, lock: "_TrackedLock") -> None:
        """Called BEFORE the underlying acquire blocks, so a deadlock in
        progress still records the edge that explains it."""
        stack = self._held_stack()
        me = id(lock)
        with self._mu:
            self._names[me] = lock.name
            for held in stack:
                if held == me:
                    continue  # reentrant misuse; RT101 is not that check
                self._add_edge(held, me)
        stack.append(me)

    def on_release(self, lock: "_TrackedLock") -> None:
        stack = self._held_stack()
        me = id(lock)
        # remove the most recent occurrence; out-of-order release is legal
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == me:
                del stack[i]
                break

    def _add_edge(self, a: int, b: int) -> None:
        """a held while acquiring b. Caller holds self._mu."""
        if b in self._edges.setdefault(a, set()):
            return
        self._edges[a].add(b)
        path = self._find_path(b, a)
        if path is not None:
            key = frozenset(path)
            if key not in self._reported_edges:
                self._reported_edges.add(key)
                names = " -> ".join(
                    self._names.get(n, f"lock@{n:#x}") for n in path + [b]
                )
                self.findings.append(
                    RuntimeFinding(
                        "RT101",
                        "lock-order cycle (potential deadlock): "
                        f"{names} — two threads acquire these locks in "
                        "opposite orders",
                    )
                )

    def _find_path(self, start: int, goal: int) -> Optional[list]:
        """DFS path start..goal in the edge graph, else None."""
        stack = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    # -- tag concurrency --------------------------------------------------

    def on_recv_enter(
        self, broker, dst: int, src: int, tag: int
    ) -> int:
        """Register a blocking recv; returns a token for
        :meth:`on_recv_exit`. Emits RT102 when an already-active waiter on
        the same mailbox can match the same messages."""
        th = threading.current_thread()
        waiter = _Waiter(
            token=next(self._token_counter),
            thread=th.ident or 0,
            thread_name=th.name,
            broker=id(broker),
            dst=dst,
            src=src,
            tag=tag,
        )
        with self._mu:
            for other in self._waiters.values():
                if waiter.overlaps(other):
                    key = (waiter.broker, dst, tag)
                    if key not in self._reported_tags:
                        self._reported_tags.add(key)
                        self.findings.append(
                            RuntimeFinding(
                                "RT102",
                                f"tag {tag} on rank {dst} is being "
                                "received concurrently by threads "
                                f"{other.thread_name!r} (src filter "
                                f"{other.src}) and "
                                f"{waiter.thread_name!r} (src filter "
                                f"{waiter.src}) — two protocol roles "
                                "share one tag; whichever matches first "
                                "steals the other's message",
                            )
                        )
            self._waiters[waiter.token] = waiter
        return waiter.token

    def on_recv_exit(self, token: int) -> None:
        with self._mu:
            self._waiters.pop(token, None)

    # -- RT103 happens-before races ---------------------------------------
    #
    # Djit+-style vector clocks. Each thread t keeps C_t; each tracked
    # lock keeps the clock its last releaser published. release(m):
    # m.vc = C_t; C_t[t] += 1. acquire(m): C_t = join(C_t, m.vc). An
    # access epoch (u, c) happens-before the current thread iff
    # c <= C_t[u] — i.e. some lock hand-off chain carried u's work here.
    # Per variable we keep the last write epoch and the reads since: a
    # write must be ordered after ALL of them, a read after the write.

    def _race_tid(self) -> int:
        tid = getattr(self._race_tids, "id", None)
        if tid is None:
            # NOT threading.get_ident(): the OS reuses those when threads
            # die, which would merge two distinct threads' clocks
            tid = self._race_tids.id = next(self._race_tid_counter)
        return tid

    def _clock(self, tid: int) -> dict:
        """Caller holds self._mu."""
        clock = self._clocks.get(tid)
        if clock is None:
            clock = self._clocks[tid] = {tid: 1}
        return clock

    def on_acquired(self, lock) -> None:
        """After the underlying acquire succeeded: join the lock's clock
        into the acquiring thread's."""
        if not self.race:
            return
        tid = self._race_tid()
        with self._mu:
            clock = self._clock(tid)
            for t, c in lock._vc.items():
                if clock.get(t, 0) < c:
                    clock[t] = c

    def on_before_release(self, lock) -> None:
        """Just before the underlying release: publish the holder's clock
        into the lock and advance the holder's own component."""
        if not self.race:
            return
        tid = self._race_tid()
        with self._mu:
            clock = self._clock(tid)
            lock._vc = dict(clock)
            clock[tid] = clock.get(tid, 1) + 1

    def on_var_access(self, key: str, write: bool) -> None:
        """An annotated shared-structure access (see module-level
        :func:`note`). Reports at most one race per key."""
        tid = self._race_tid()
        tname = threading.current_thread().name
        # drop the note()/on_var_access frames; keep the caller's tail
        stack = "".join(
            traceback.format_list(traceback.extract_stack()[-8:-2])
        )
        with self._mu:
            clock = self._clock(tid)
            st = self._vars.setdefault(key, {"w": None, "r": {}})

            def _ordered(epoch) -> bool:
                e_tid, e_clk, _, _ = epoch
                return e_clk <= clock.get(e_tid, 0) or e_tid == tid

            race, kind = None, None
            if st["w"] is not None and not _ordered(st["w"]):
                race = st["w"]
                kind = "write-write" if write else "read-write"
            if write and race is None:
                for prev in st["r"].values():
                    if not _ordered(prev):
                        race, kind = prev, "read-write"
                        break
            if race is not None and key not in self._reported_races:
                self._reported_races.add(key)
                o_tid, _, o_name, o_stack = race
                self.findings.append(
                    RuntimeFinding(
                        "RT103",
                        f"{kind} race on {key}: no happens-before edge "
                        f"between thread {o_name!r} (t{o_tid}) at:\n"
                        f"{o_stack}  and thread {tname!r} (t{tid}) at:\n"
                        f"{stack}  — the accesses can interleave; guard "
                        "both with one tracked lock",
                    )
                )
            me = (tid, clock.get(tid, 1), tname, stack)
            if write:
                st["w"] = me
                st["r"] = {}
            else:
                st["r"][tid] = me

    # -- RT104 numerics sanitizer -------------------------------------------
    #
    # Armed-only cost (every hook is behind ``checker.numerics``); numpy
    # is imported lazily inside the methods so this module stays
    # stdlib-only at import time for the reader tools that sit on it.

    #: EF-residual boundedness: a norm this many times the largest norm
    #: seen in the first observations of a stream is divergence, not the
    #: bounded O(scale) rounding floor the EF recurrence guarantees
    RESIDUAL_GROWTH_BOUND = 1000.0
    _RESID_WARMUP = 3

    def _numerics_site(self) -> tuple:
        """(file:line, stack tail) of the first frame outside this module
        and quant.py — the USER call site, so one buggy caller reports
        once however many chunks it pushes."""
        frames = traceback.extract_stack()[:-3]
        skip = (os.sep + "quant.py", os.sep + "runtime.py")
        caller = None
        for fr in reversed(frames):
            if not fr.filename.endswith(skip):
                caller = fr
                break
        where = (
            f"{caller.filename}:{caller.lineno}" if caller else "<unknown>"
        )
        stack = "".join(traceback.format_list(frames[-6:]))
        return where, stack

    def _numerics_report(self, kind: str, message: str) -> None:
        where, stack = self._numerics_site()
        with self._mu:
            if (where, kind) in self._reported_numerics:
                return
            self._reported_numerics.add((where, kind))
            self.findings.append(
                RuntimeFinding(
                    "RT104", f"{message} at {where}:\n{stack}"
                )
            )

    def on_quantize(self, face: str, arr, mode: str, scale, codes) -> None:
        """Called by the host quant kernels (quant.py) when armed."""
        import numpy as np

        a = np.asarray(arr)
        n_bad = int(a.size - np.count_nonzero(np.isfinite(a)))
        if n_bad:
            self._numerics_report(
                "non-finite-input",
                f"{n_bad} non-finite value(s) reached {face}[{mode}] "
                f"(shape {a.shape}) — a NaN/Inf is about to cross the "
                "wire; the quantizer pins it, but the producer is broken",
            )
        if mode != "int8" or not a.size:
            return
        s = np.asarray(scale)
        if not bool(np.all(np.isfinite(s))) or not bool(np.all(s > 0)):
            self._numerics_report(
                "scale-overflow",
                f"{face}[int8] produced a non-finite or non-positive "
                f"scale (absmax overflow) — codes are garbage",
            )
            return
        # the zero-absmax pin (quant.py's hardened contract): a row with
        # no finite signal must quantize to scale 1 / all-zero codes so
        # it dequantizes to exact zeros
        finite_amax = np.max(
            np.where(np.isfinite(a), np.abs(a), 0),
            axis=-1 if s.ndim else None,
        )
        c = np.asarray(codes)
        zero_rows = finite_amax == 0
        if bool(np.any(zero_rows)):
            row_codes = c if not s.ndim else c[np.asarray(zero_rows)]
            if bool(np.any(row_codes)):
                self._numerics_report(
                    "zero-absmax",
                    f"{face}[int8] emitted nonzero codes for a "
                    "zero-absmax row — the hardened zero/NaN pin "
                    "regressed; dequantize will fabricate signal",
                )

    def on_dequantize(self, face: str, scale, mode: str) -> None:
        import numpy as np

        if mode != "int8":
            return
        s = np.asarray(scale)
        if not bool(np.all(np.isfinite(s))) or not bool(np.all(s > 0)):
            self._numerics_report(
                "bad-dequant-scale",
                f"{face}[int8] called with a non-finite or non-positive "
                "scale — the codes' scale was dropped or corrupted in "
                "transit",
            )

    def on_numeric_array(self, site: str, arr) -> None:
        """NaN/Inf check on a host-boundary array (server apply path,
        collective accumulation exits). Traced values don't convert —
        callers only hand in concrete host arrays."""
        import numpy as np

        try:
            a = np.asarray(arr)
        except Exception:
            return  # a tracer or non-array: not checkable here
        if a.dtype.kind != "f":
            return
        n_bad = int(a.size - np.count_nonzero(np.isfinite(a)))
        if n_bad:
            self._numerics_report(
                f"nonfinite:{site}",
                f"{n_bad} non-finite value(s) in {site} "
                f"(shape {a.shape}) — poisoned state is being applied",
            )

    def on_residual_norm(self, key: str, norm: float) -> None:
        """EF-residual boundedness, cross-checked against the same norm
        the dynamics plane journals as ``elastic``: the residual is the
        quantizer's one-step rounding error and must stay O(scale) —
        finite always, and never orders of magnitude above the stream's
        early rounds."""
        import math

        if not math.isfinite(norm):
            self._numerics_report(
                f"resid-nonfinite:{key}",
                f"error-feedback residual norm for {key} is {norm!r} — "
                "the EF state is poisoned and every future push "
                "inherits it",
            )
            return
        with self._mu:
            seen = self._resid_norms.setdefault(key, [])
            if len(seen) < self._RESID_WARMUP:
                seen.append(norm)
                return
            bound = self.RESIDUAL_GROWTH_BOUND * max(max(seen), 1e-12)
        if norm > bound:
            self._numerics_report(
                f"resid-growth:{key}",
                f"error-feedback residual norm for {key} reached "
                f"{norm:.3e}, over {self.RESIDUAL_GROWTH_BOUND:.0f}x the "
                "warmup rounds' ceiling — the EF recurrence is diverging "
                "instead of carrying bounded rounding error",
            )


class _TrackedLock:
    """threading.Lock wrapper reporting acquisition order to a checker.

    Bound to the checker active at CREATION time, so a checker torn down
    mid-flight (the ``checking()`` block exited while a transport thread
    still runs) keeps receiving events instead of the thread crashing."""

    def __init__(self, name: str, checker: RuntimeChecker):
        self._lock = threading.Lock()
        self.name = name
        self._checker = checker
        self._vc: dict = {}  # RT103: last releaser's vector clock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._checker.on_acquire(self)
        got = self._lock.acquire(blocking, timeout)
        if not got:
            self._checker.on_release(self)
        else:
            self._checker.on_acquired(self)
        return got

    def release(self) -> None:
        self._checker.on_before_release(self)
        self._lock.release()
        self._checker.on_release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class _TrackedCondition:
    """threading.Condition wrapper with the same RT101/RT103 hooks as
    :class:`_TrackedLock` — ``with cond:`` IS a lock acquisition, and
    ``wait()`` is a release/reacquire pair for the clock algebra (the
    hand-off from ``notify``'s releaser to the woken waiter flows through
    the publish-on-release / join-on-acquire edges)."""

    def __init__(self, name: str, checker: RuntimeChecker):
        self._cond = threading.Condition()
        self.name = name
        self._checker = checker
        self._vc: dict = {}

    def acquire(self, *args) -> bool:
        self._checker.on_acquire(self)
        got = self._cond.acquire(*args)
        if not got:
            self._checker.on_release(self)
        else:
            self._checker.on_acquired(self)
        return got

    def release(self) -> None:
        self._checker.on_before_release(self)
        self._cond.release()
        self._checker.on_release(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._checker.on_before_release(self)
        self._checker.on_release(self)
        try:
            return self._cond.wait(timeout)
        finally:
            self._checker.on_acquire(self)
            self._checker.on_acquired(self)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # threading.Condition.wait_for's loop, routed through our wait()
        # so every park/wake keeps the clock algebra consistent
        import time as _time

        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


_ACTIVE: Optional[RuntimeChecker] = None


def active_checker() -> Optional[RuntimeChecker]:
    return _ACTIVE


def make_lock(name: str):
    """The transport lock factory: a plain ``threading.Lock`` normally, a
    tracked lock while a checker is active. ``name`` is the diagnostic
    role label (instances may share it; identity drives the graph)."""
    checker = _ACTIVE
    if checker is None:
        return threading.Lock()
    return _TrackedLock(name, checker)


def make_condition(name: str):
    """Sibling factory for condition variables (Broker mailboxes, send
    queues): plain ``threading.Condition`` when no checker is active."""
    checker = _ACTIVE
    if checker is None:
        return threading.Condition()
    return _TrackedCondition(name, checker)


def note(key: str, write: bool) -> None:
    """Annotate one access to a shared structure for RT103. Free when no
    race-mode checker is active — the instrumented hot paths pay one
    global read and one attribute check."""
    checker = _ACTIVE
    if checker is not None and checker.race:
        checker.on_var_access(key, write)


def note_numeric_array(site: str, arr) -> None:
    """Annotate one host-boundary array for RT104 (server apply path,
    collective-accumulation exits). Free when no numerics-mode checker
    is active."""
    checker = _ACTIVE
    if checker is not None and checker.numerics:
        checker.on_numeric_array(site, arr)


def note_residual_norm(key: str, norm: float) -> None:
    """Annotate one error-feedback residual norm for RT104 — callers
    hand in the SAME value the dynamics plane journals as ``elastic``,
    so the sanitizer and the journal can never disagree about what the
    residual was."""
    checker = _ACTIVE
    if checker is not None and checker.numerics:
        checker.on_residual_norm(key, float(norm))


def enable(
    checker: Optional[RuntimeChecker] = None,
    race: bool = False,
    numerics: bool = False,
) -> RuntimeChecker:
    global _ACTIVE
    _ACTIVE = checker or RuntimeChecker(race=race, numerics=numerics)
    return _ACTIVE


def disable() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def checking(
    race: bool = False, numerics: bool = False
) -> Iterator[RuntimeChecker]:
    """Enable a fresh checker for the block; disables on exit (the checker
    object and its findings stay readable afterwards)."""
    checker = enable(race=race, numerics=numerics)
    try:
        yield checker
    finally:
        disable()


def _env_on(name: str) -> bool:
    return os.environ.get(name, "0") not in ("", "0")


def _arm_from_env() -> None:
    """``MPIT_RT_RACE=1`` / ``MPIT_RT_NUMERICS=1`` arm one shared
    process-wide checker (each launch.py rank imports this module early,
    so transport locks are created tracked and the quant kernels see the
    checker) and report findings at exit — the chaos-soak wiring. Each
    armed plane prints its own banner and its own finding count, so the
    soak can gate the two independently."""
    race, numerics = _env_on("MPIT_RT_RACE"), _env_on("MPIT_RT_NUMERICS")
    if not race and not numerics:
        return
    checker = enable(race=race, numerics=numerics)
    if race:
        print(
            "[rt-race] vector-clock race sanitizer armed "
            f"(pid {os.getpid()})",
            file=sys.stderr,
        )
    if numerics:
        print(
            f"[rt-numerics] numerics sanitizer armed (pid {os.getpid()})",
            file=sys.stderr,
        )
    import atexit

    @atexit.register
    def _report() -> None:
        for finding in checker.findings:
            print(finding.format(), file=sys.stderr)
        if race:
            n = sum(1 for f in checker.findings if f.rule != "RT104")
            print(f"[rt-race] {n} finding(s)", file=sys.stderr)
        if numerics:
            n = sum(1 for f in checker.findings if f.rule == "RT104")
            print(f"[rt-numerics] {n} finding(s)", file=sys.stderr)


_arm_from_env()
