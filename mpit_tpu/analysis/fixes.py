"""``--fix`` rewrites for MPT002: literal transport tag → ``TAG_*`` name.

A hard-coded ``transport.send(dst, 2, x)`` bypasses the tag registry; when
the literal is a KNOWN protocol tag (a value with exactly one ``TAG_*``
name in the canonical registry extracted from ``mpit_tpu/parallel/`` —
1–6 today), the call is mechanically rewritable: replace the literal with
its registry name and add the import. That is what this module does,
behind ``python -m mpit_tpu.analysis --fix``.

Scope is deliberately narrow — this is the one rule whose fix is a pure,
behavior-preserving identity (the integer on the wire is unchanged):

- only int literals whose value maps to exactly ONE registry name are
  rewritten (ambiguous or unknown values — e.g. the fixture's ``42`` —
  are left for a human);
- lines carrying an ``# mpit-analysis: ignore`` for MPT002 are left
  alone (a suppressed finding is a decision already made);
- the import (``from mpit_tpu.parallel.pserver import TAG_X, ...``) is
  inserted after the last top-level import — or after the module
  docstring when there are none — unless the name is already bound at
  module level;
- files are rewritten in place and re-parsed afterwards; a rewrite that
  would not parse is abandoned (original content kept) and reported.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Optional

from mpit_tpu.analysis import lint
from mpit_tpu.analysis.graph import module_name_for_rel
from mpit_tpu.analysis.rules import tags as tags_rule


@dataclasses.dataclass
class FileFix:
    """What ``--fix`` did (or could not do) to one file."""

    path: Path
    replaced: int = 0  # literal sites rewritten
    imported: tuple = ()  # names a new import line now provides
    skipped: int = 0  # known-literal sites left alone (ignored lines)
    error: Optional[str] = None


def registry_by_value() -> dict:
    """value -> TAG_* name, for values with exactly one canonical name
    (an ambiguous value cannot be fixed mechanically), plus the defining
    module per name."""
    names_by_value: dict = {}
    module_by_name: dict = {}
    for t in tags_rule._canonical_registry():
        names_by_value.setdefault(t.value, set()).add(t.name)
        module_by_name[t.name] = module_name_for_rel(t.rel)
    return {
        value: (next(iter(names)), module_by_name[next(iter(names))])
        for value, names in names_by_value.items()
        if len(names) == 1
    }


def _module_level_names(tree: ast.Module) -> set:
    """Names already bound at module level (imports, defs, assigns) — an
    import line must not shadow or duplicate them."""
    bound: set = set()
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name != "*":
                    bound.add(alias.asname or alias.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bound.add(tgt.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            bound.add(node.target.id)
    return bound


def _import_insert_line(tree: ast.Module) -> int:
    """0-based line index AFTER which a new import belongs: the last
    top-level import, else the module docstring, else the top."""
    last = 0
    for i, node in enumerate(tree.body):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            last = node.end_lineno
        elif (
            i == 0
            and isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            last = node.end_lineno
    return last


def fix_file(path: Path, registry: Optional[dict] = None) -> FileFix:
    """Rewrite every fixable literal-tag site in one file, in place."""
    result = FileFix(path=path)
    registry = registry_by_value() if registry is None else registry
    if not registry:
        return result
    ctx = lint.load_module(path, path.name)
    if ctx is None:
        result.error = "unreadable or not parseable"
        return result
    lines = list(ctx.source_lines)
    edits = []  # (lineno, col, end_col, name)
    needed: dict = {}  # name -> defining module
    for _call, tag_node, val in tags_rule.iter_literal_tag_sites(ctx.tree):
        if val not in registry:
            continue
        ignored = ctx.ignores.get(tag_node.lineno, ())
        if "*" in ignored or "MPT002" in ignored:
            result.skipped += 1
            continue
        if tag_node.lineno != tag_node.end_lineno:
            continue  # a multi-line int literal is not a thing we emit
        name, module = registry[val]
        edits.append(
            (tag_node.lineno, tag_node.col_offset,
             tag_node.end_col_offset, name)
        )
        needed[name] = module
    if not edits:
        return result
    # apply right-to-left so earlier columns stay valid
    for lineno, col, end_col, name in sorted(edits, reverse=True):
        line = lines[lineno - 1]
        lines[lineno - 1] = line[:col] + name + line[end_col:]
    bound = _module_level_names(ctx.tree)
    missing = {n: m for n, m in needed.items() if n not in bound}
    if missing:
        insert_at = _import_insert_line(ctx.tree)
        by_module: dict = {}
        for name, module in missing.items():
            by_module.setdefault(module, []).append(name)
        for module in sorted(by_module, reverse=True):
            names = ", ".join(sorted(by_module[module]))
            lines.insert(insert_at, f"from {module} import {names}")
        result.imported = tuple(sorted(missing))
    new_source = "\n".join(lines) + ("\n" if lines else "")
    try:
        ast.parse(new_source)
    except SyntaxError as e:  # never leave a broken file behind
        result.error = f"rewrite would not parse ({e}); file unchanged"
        return result
    path.write_text(new_source)
    result.replaced = len(edits)
    return result


def fix_paths(paths: Iterable) -> list:
    """Fix every .py under ``paths``; returns the per-file results that
    did something (or failed)."""
    registry = registry_by_value()
    out = []
    for ap, _rel in lint.collect_files(paths):
        r = fix_file(ap, registry)
        if r.replaced or r.skipped or r.error:
            out.append(r)
    return out
