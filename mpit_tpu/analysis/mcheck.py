"""Explicit-state model checker for the PS fetch/push protocol.

The linter's MPT008 pairs tags; this module goes further and *verifies*
the protocol semantics that :func:`mpit_tpu.analysis.protocol
.extract_semantics` lifts out of the marked modules (attempt-id echo +
check, reply-wait timeout, the dedup window's exact boundary) by
exhaustively exploring every message interleaving of a small
configuration under the chaos fault vocabulary:

- ``drop``       — the message is never delivered;
- ``dup``        — delivered twice, the second copy out of order;
- ``reorder``    — delivered, but possibly out of stream order;
- ``stale``      — a reply delayed past the requester's timeout (it can
                   still arrive later, racing the retry's fresh reply).

At most ONE fault is injected per run, but the *choice* of fault is part
of the state space: at every send the checker branches into the clean
send plus every applicable (kind, message) fault, so a single
breadth-bounded exploration covers the fault-free baseline and every
single-fault schedule at once, with all shared prefixes/suffixes
deduplicated through the visited set. STOP messages are never faulted —
teardown loss is the watchdog's jurisdiction (docs/ROBUSTNESS.md), not
the exchange protocol's.

Verified safety properties (reported as lint rules by
``rules/model_check.py``):

- **MPT009** exactly-once push application: no ``(client, seq)`` push is
  ever applied twice by one server (the dedup window's contract);
- **MPT010** deadlock freedom: no reachable state where nobody can move
  yet the run isn't finished (every blocking recv has an escape);
- **MPT011** stale-attempt isolation: a reply generated for attempt *i*
  is never accepted by a client whose live attempt is *j* ≠ *i* (the
  mis-assembled-fetch bug the attempt-id echo exists to prevent).

The model is deliberately small and immutable: states are nested tuples,
transitions are pure functions, and the whole exploration is a stack +
visited-set loop. Client steps and the server's handle-and-reply are
atomic (matching the implementation: both run under one dispatch
iteration), messages are FIFO per ``(kind, src, dst)`` stream except
where a fault marked them reorderable, and a client's timeout transition
is enabled exactly when no in-flight message could still satisfy its
wait (or the only candidate reply is stale-delayed) — the model's
version of "the timer really would fire first".
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# message kinds (single chars: states hash millions of times)
K_REQ, K_REP, K_PUSH, K_STOP = "Q", "P", "U", "S"
# message flag bits
RE = 1  # reorderable: may be delivered ahead of/behind its stream
STALE = 2  # a reply delayed past the requester's timeout

FAULT_KINDS = ("drop", "dup", "reorder", "stale")

_KIND_LABEL = {K_REQ: "REQ", K_REP: "REPLY", K_PUSH: "PUSH", K_STOP: "STOP"}


@dataclasses.dataclass(frozen=True)
class DedupModel:
    """The admit predicate's modeled bits (window size comes from the
    config — exploring a 1024-wide window would need 1025 rounds to
    exercise the boundary, so the model shrinks it instead)."""

    rejects_at_boundary: bool
    checks_seen: bool
    prunes_seen: bool


@dataclasses.dataclass(frozen=True)
class ModelSemantics:
    """What the checked protocol does about faults (see
    ``protocol.ProtocolSemantics``; this is its model-facing projection,
    constructible directly in tests)."""

    attempt_echoed: bool
    attempt_checked: bool
    reply_recv_timeout: bool
    has_push: bool
    dedup: Optional[DedupModel]
    dedup_opaque: bool = False  # dedup exists but unmodelable: assume ok
    #: the window is keyed per client incarnation (the ``(src, epoch)``
    #: idiom) — a replacement client gets a fresh dedup slot
    dedup_keyed_by_epoch: bool = False
    #: the server's shard snapshot persists the dedup window WITH the
    #: center/applied state (True), without it (False — the
    #: crash-consistency bug the elastic config exists to catch), or
    #: there is no snapshot machinery at all (None — restart schedules
    #: still run, modeling restart-from-nothing)
    snapshot_includes_dedup: Optional[bool] = None
    #: a shard HANDOFF ships the dedup entries along with the shard data
    #: (True), ships the data but forgets the window (False — the
    #: exactly-once-across-handoff bug the sharded config exists to
    #: catch), or the protocol has no handoff machinery at all (None —
    #: the sharded configuration is skipped)
    handoff_carries_dedup: Optional[bool] = None


def from_protocol(sem) -> ModelSemantics:
    """ModelSemantics from a ``protocol.ProtocolSemantics``."""
    dedup = None
    keyed = False
    if sem.dedup is not None:
        dedup = DedupModel(
            rejects_at_boundary=sem.dedup.rejects_at_boundary,
            checks_seen=sem.dedup.checks_seen,
            prunes_seen=sem.dedup.prunes_seen,
        )
        keyed = sem.dedup.keyed_by_epoch
    return ModelSemantics(
        attempt_echoed=sem.attempt_echoed,
        attempt_checked=sem.attempt_checked,
        reply_recv_timeout=sem.reply_recv_timeout,
        has_push=bool(sem.push_tags),
        dedup=dedup,
        dedup_opaque=sem.dedup_opaque,
        dedup_keyed_by_epoch=keyed,
        snapshot_includes_dedup=sem.snapshot_includes_dedup,
        handoff_carries_dedup=getattr(sem, "handoff_includes_dedup", None),
    )


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """One exploration's bounds. The defaults are the acceptance
    configuration: 2 clients x 1 server, 2 rounds, dedup window 1 (the
    smallest window with a boundary), 1 retry."""

    algo: str = "easgd"
    script: tuple = ("fetch", "push")  # one round's client steps
    clients: int = 2
    servers: int = 1
    rounds: int = 2
    window: int = 1
    max_retries: int = 1
    kinds: tuple = FAULT_KINDS
    max_states: int = 500_000
    #: elastic membership mode: clients carry an incarnation counter and
    #: may be REPLACED mid-run (preemption + respawn from step 0, fresh
    #: epoch), servers may snapshot and CRASH-RESTORE — a second,
    #: independent single-fault budget on top of the network one
    elastic: bool = False
    #: sharded-ownership mode (implies the elastic crash machinery):
    #: parameters live in ``shards`` ring-placed shards whose ownership
    #: can move between servers mid-run via a HANDOFF transition (its
    #: own one-shot budget, independent of both fault budgets); pushes
    #: are routed to the shard's CURRENT owner at delivery — the model's
    #: version of the client-side reshard repair
    sharded: bool = False
    shards: int = 2
    #: spend the network-fault budget on PUSH messages only (REQ/REP
    #: fault coverage is the base configs' jurisdiction) — the sharded
    #: config uses this to keep handoff x crash x fault exhaustive
    fault_push_only: bool = False

    @property
    def label(self) -> str:
        return (
            f"{self.algo}, {self.clients} client(s) x "
            f"{self.servers} server(s), {self.rounds} round(s)"
        )


def default_configs(has_push: bool, quick: bool = False) -> tuple:
    """The two shipped-protocol configurations: EASGD (fetch -> push)
    and Downpour (push -> fetch). A push-less protocol gets a single
    fetch-only config (the scripts would coincide).

    ``quick=True`` drops to 1 client (~300-400 states each vs ~12-20k):
    the single-fault hazards these configs witness — the dedup boundary
    re-admit, the stale reply, the block-forever recv — are all
    per-client-per-server, so one client keeps every seeded-mutation
    witness (verified per fixture in tests/test_analysis.py) while the
    pre-commit scan stays cheap; test_mcheck.py runs the 2-client
    acceptance pair."""
    clients = 1 if quick else 2
    if not has_push:
        return (
            ModelConfig(algo="fetch-only", script=("fetch",),
                        clients=clients),
        )
    return (
        ModelConfig(algo="easgd", script=("fetch", "push"),
                    clients=clients),
        ModelConfig(algo="downpour", script=("push", "fetch"),
                    clients=clients),
    )


def elastic_config() -> ModelConfig:
    """The membership-churn configuration: 1 client whose process can be
    replaced mid-run + 1 server that can snapshot and crash-restore.
    One client is enough — the elastic hazards (a replacement's re-used
    seqs vs the predecessor's window; a restored server's dedup vs its
    restored applied set) are per-client-per-server, and the second
    fault budget already multiplies the interleavings."""
    return ModelConfig(
        algo="easgd-elastic",
        script=("fetch", "push"),
        clients=1,
        servers=1,
        rounds=2,
        elastic=True,
    )


def sharded_config(quick: bool = False) -> ModelConfig:
    """The shard-ownership configuration: 2 clients x 2 servers, 2 ring
    shards (initially one per server), with a one-shot HANDOFF budget on
    top of the network-fault and crash-restore budgets. Two servers are
    the minimum with somewhere for a shard to move; two clients make the
    handed-off dedup state multi-sourced. Client REPLACE is disabled
    here (the elastic config already owns that hazard) to keep the
    handoff x crash x fault product exhaustive within budget.

    ``quick=True`` is the lint-tier variant (1 client, ~1k states vs
    ~100k): every handoff hazard that is per-client-per-server — the
    dedup window forgotten in transit, the replayed push after the
    move — still has a witness, so the pre-commit scan stays inside its
    wall-clock budget while test_mcheck.py owns the full 2-client
    exhaustive acceptance run."""
    return ModelConfig(
        algo="easgd-sharded",
        script=("fetch", "push"),
        clients=1 if quick else 2,
        servers=2,
        rounds=1,
        kinds=("drop", "dup"),
        elastic=True,
        sharded=True,
        shards=2,
        fault_push_only=True,
    )


@dataclasses.dataclass
class CheckResult:
    config: ModelConfig
    states: int  # distinct states explored
    fault_points: int  # distinct (kind, message) single-fault schedules
    violations: dict  # rule id -> witness message
    truncated: bool  # hit max_states (result then inconclusive)

    @property
    def ok(self) -> bool:
        return not self.violations and not self.truncated


# -- transitions ------------------------------------------------------------
#
# state  = (clients, servers, net, fault_available)
# client = (stage, waiting, attempt, retries, pending_servers)
#          stage 0..n_stages-1 = script step; n_stages = send STOP;
#          n_stages+1 = done
# server = (stops, applied, dedup) with dedup = ((high, seen), ...) per
#          client; applied = frozenset of (client, seq)
# msg    = (kind, src, dst, a, b, flags)
#          REQ: a=attempt          REP: a=true_attempt, b=echo (-1 none)
#          PUSH: a=seq             STOP: —
#
# elastic mode (cfg.elastic) extends every shape by one slot:
# state  = (clients, servers, net, fault_available, elastic_available)
# client = (stage, waiting, attempt, retries, pending, inc) — inc is the
#          incarnation (the model's epoch); a REPLACE resets the client
#          to stage 0 with inc+1 (a respawned process re-runs from step
#          0) while attempt ids keep counting up (the implementation
#          seeds them from the fresh epoch, so a replacement's ids are
#          disjoint from its predecessor's by construction)
# server = (stops, applied, dedup, snap) — applied keyed (c, inc, seq);
#          dedup[c] is a TUPLE of per-inc windows when the extracted
#          window is epoch-keyed, else a 1-tuple shared window; snap is
#          None until the server takes its (applied, dedup-or-None)
#          shard snapshot, after which CRASH restores from it (stops
#          survive a crash: the membership view is in the snapshot)
# PUSH   = (K_PUSH, c, s, seq, inc, flags) — the b slot carries inc


def _canon(net) -> tuple:
    """Canonical network order. Delivery semantics only constrain the
    relative order WITHIN a non-reorderable (kind, src, dst) stream;
    interleavings across streams (and among reorderable messages) are
    equivalent, so states are stored with streams sorted by key and the
    reorderable pool sorted — collapsing k! permutations of k independent
    sends into one state."""
    if len(net) <= 1:
        return net
    streams: dict = {}
    loose = []
    for m in net:
        if m[5] & RE:
            loose.append(m)
        else:
            streams.setdefault((m[0], m[1], m[2]), []).append(m)
    out = []
    for key in sorted(streams):
        out.extend(streams[key])
    out.extend(sorted(loose))
    return tuple(out)


def _deliverable(net) -> list:
    """Indices deliverable now: the head non-reorderable message of each
    (kind, src, dst) stream, plus every reorderable message."""
    out = []
    seen_head = set()
    for i, m in enumerate(net):
        if m[5] & RE:
            out.append(i)
            continue
        key = (m[0], m[1], m[2])
        if key not in seen_head:
            out.append(i)
            seen_head.add(key)
    return out


def _variants(msgs, avail, kinds, points) -> list:
    """Fault branching for one atomic multi-send: the clean send, plus —
    when the single-fault budget is unspent — each applicable fault on
    each message. Returns [(messages_to_enqueue, fault_still_available)].
    """
    base = tuple(msgs)
    out = [(base, avail)]
    if not avail:
        return out
    for i, m in enumerate(msgs):
        if m[0] == K_STOP:
            continue  # teardown is never faulted (see module docstring)
        for kind in kinds:
            if kind == "drop":
                repl = ()
            elif kind == "dup":
                repl = (m, m[:5] + (m[5] | RE,))
            elif kind == "reorder":
                repl = (m[:5] + (m[5] | RE,),)
            elif kind == "stale" and m[0] == K_REP:
                repl = (m[:5] + (m[5] | RE | STALE,),)
            else:
                continue
            points.add((kind, m[:5]))
            out.append((base[:i] + repl + base[i + 1:], False))
    return out


def _set(tup, i, v):
    return tup[:i] + (v,) + tup[i + 1:]


def _apply_push(servers, s, c, seq, sem, cfg, viol):
    """One server consuming one push: run the modeled admit predicate,
    then the exactly-once assertion on the applied set."""
    stops, applied, dedup = servers[s]
    ds = dedup
    if sem.dedup is not None:
        high, seen = dedup[c]
        bound = high - cfg.window
        if sem.dedup.rejects_at_boundary:
            reject = seq <= bound
        else:
            reject = seq < bound
        if not reject and sem.dedup.checks_seen and seq in seen:
            reject = True
        admitted = not reject
        if admitted:
            seen2 = seen | {seq}
            if seq > high:
                if sem.dedup.prunes_seen and len(seen2) > cfg.window:
                    floor = seq - cfg.window
                    seen2 = frozenset(x for x in seen2 if x > floor)
                ds = _set(dedup, c, (seq, frozenset(seen2)))
            else:
                ds = _set(dedup, c, (high, frozenset(seen2)))
    elif sem.dedup_opaque:
        # unmodelable dedup machinery: assume it deduplicates correctly
        # (resolve-or-skip — never report what we couldn't model)
        admitted = (c, seq) not in applied
    else:
        admitted = True  # no dedup at all: every delivery applies
    if admitted:
        if (c, seq) in applied:
            viol.setdefault(
                "MPT009",
                f"[{cfg.label}] push (client {c}, seq {seq}) applied "
                "TWICE by one server: a duplicated/reordered copy passed "
                "the dedup admit after the window slid past it",
            )
        applied = applied | {(c, seq)}
    return _set(servers, s, (stops, applied, ds))


def _fresh_dedup(cfg) -> tuple:
    """Elastic-mode zero dedup state: one empty window per client (the
    keyed variant grows extra per-incarnation windows lazily)."""
    return tuple(((0, frozenset()),) for _ in range(cfg.clients))


def _apply_push_elastic(servers, s, c, seq, inc, sem, cfg, viol):
    """Elastic-mode push application: the window is selected per
    incarnation when the extracted dedup is epoch-keyed (a replacement
    gets a fresh slot), shared otherwise — where a replacement's
    re-used seqs collide with its predecessor's seen-set, the
    wrongful-rejection half of MPT009."""
    stops, applied, dedup, snap = servers[s]
    key = (c, inc, seq)
    keyed = sem.dedup_keyed_by_epoch
    ds = dedup
    if sem.dedup is not None:
        windows = dedup[c]
        idx = inc if keyed else 0
        while len(windows) <= idx:
            windows = windows + ((0, frozenset()),)
        high, seen = windows[idx]
        bound = high - cfg.window
        if sem.dedup.rejects_at_boundary:
            reject = seq <= bound
        else:
            reject = seq < bound
        if not reject and sem.dedup.checks_seen and seq in seen:
            reject = True
        admitted = not reject
        if admitted:
            seen2 = seen | {seq}
            if seq > high:
                if sem.dedup.prunes_seen and len(seen2) > cfg.window:
                    floor = seq - cfg.window
                    seen2 = frozenset(x for x in seen2 if x > floor)
                windows = _set(windows, idx, (seq, frozenset(seen2)))
            else:
                windows = _set(windows, idx, (high, frozenset(seen2)))
        ds = _set(dedup, c, windows)
    elif sem.dedup_opaque:
        admitted = key not in applied
    else:
        admitted = True
    if admitted:
        if key in applied:
            viol.setdefault(
                "MPT009",
                f"[{cfg.label}] push (client {c}, incarnation {inc}, "
                f"seq {seq}) applied TWICE by one server: a redelivered "
                "copy passed the dedup admit after a crash-restore lost "
                "the window state that had recorded it",
            )
        applied = applied | {key}
    elif (
        sem.dedup is not None
        and not keyed
        and key not in applied
        and any(t[0] == c and t[2] == seq and t[1] != inc for t in applied)
    ):
        # the window is NOT keyed by incarnation: this fresh push was
        # swallowed because a PREVIOUS incarnation of the client used
        # the same seq — a replacement silently loses its first pushes
        viol.setdefault(
            "MPT009",
            f"[{cfg.label}] push (client {c}, incarnation {inc}, seq "
            f"{seq}) wrongfully REJECTED: the dedup window is not keyed "
            "by client epoch, so the replacement process's push was "
            "mistaken for its predecessor's replay and dropped",
        )
    return _set(servers, s, (stops, applied, ds, snap))


def _starved(net, c, att, pending, sem) -> bool:
    """Would the client's reply wait really time out? True when some
    pending server has neither a live same-attempt REQ in flight nor a
    reply that this client would take; stale-delayed replies don't count
    (being delayed past the timeout is their definition)."""
    distinguishes = sem.attempt_echoed and sem.attempt_checked
    satisfied = set()
    for m in net:
        if m[0] == K_REQ and m[1] == c and m[3] == att:
            satisfied.add(m[2])
        elif m[0] == K_REP and m[2] == c and not (m[5] & STALE):
            if not distinguishes or m[4] == att:
                satisfied.add(m[1])
    return any(s not in satisfied for s in pending)


def _successors(state, sem, cfg, viol, points) -> list:
    clients, servers, net, avail = state
    out = []
    deliv = _deliverable(net)
    steps = len(cfg.script)
    n_stages = cfg.rounds * steps
    all_clients = frozenset(range(cfg.clients))

    # -- server deliveries (handle + reply are one atomic step)
    for i in deliv:
        m = net[i]
        kind = m[0]
        if kind == K_REP:
            continue
        s = m[2]
        stops = servers[s][0]
        if stops == all_clients:
            continue  # server exited its loop; late messages park
        rest = net[:i] + net[i + 1:]
        if kind == K_REQ:
            c, att = m[1], m[3]
            echo = att if sem.attempt_echoed else -1
            rep = (K_REP, s, c, att, echo, 0)
            for added, av2 in _variants([rep], avail, cfg.kinds, points):
                out.append((clients, servers, rest + added, av2))
        elif kind == K_PUSH:
            srv2 = _apply_push(servers, s, m[1], m[3], sem, cfg, viol)
            out.append((clients, srv2, rest, avail))
        else:  # STOP
            srv2 = _set(
                servers, s, (stops | {m[1]}, servers[s][1], servers[s][2])
            )
            out.append((clients, srv2, rest, avail))

    # -- client moves
    for c, cl in enumerate(clients):
        stage, waiting, att, retries, pending = cl
        if stage > n_stages:
            continue  # done
        if waiting:
            for i in deliv:
                m = net[i]
                if m[0] != K_REP or m[2] != c:
                    continue
                rest = net[:i] + net[i + 1:]
                true_att, s = m[3], m[1]
                if true_att != att:
                    if sem.attempt_echoed and sem.attempt_checked:
                        # stale reply detected and dropped (consumed)
                        out.append((clients, servers, rest, avail))
                        continue
                    viol.setdefault(
                        "MPT011",
                        f"[{cfg.label}] client {c} assembled a reply "
                        f"generated for attempt {true_att} into its live "
                        f"attempt {att} — "
                        + (
                            "the echoed attempt id is never compared "
                            "to the live one"
                            if sem.attempt_echoed
                            else "replies carry no attempt id, so stale "
                            "ones are indistinguishable from fresh"
                        ),
                    )
                pend2 = pending - {s}
                if pend2:
                    cl2 = (stage, True, att, retries, pend2)
                else:
                    cl2 = (stage + 1, False, att, 0, frozenset())
                out.append((_set(clients, c, cl2), servers, rest, avail))
            if sem.reply_recv_timeout and _starved(
                net, c, att, pending, sem
            ):
                if retries < cfg.max_retries:
                    att2 = att + 1
                    reqs = [
                        (K_REQ, c, s, att2, 0, 0) for s in sorted(pending)
                    ]
                    cl2 = (stage, True, att2, retries + 1, pending)
                    for added, av2 in _variants(
                        reqs, avail, cfg.kinds, points
                    ):
                        out.append(
                            (_set(clients, c, cl2), servers, net + added,
                             av2)
                        )
                else:
                    # retries exhausted: skip the round (the ps_roles
                    # graceful-degradation path), resume next round
                    stage2 = (stage // steps + 1) * steps
                    cl2 = (stage2, False, att, 0, frozenset())
                    out.append(
                        (_set(clients, c, cl2), servers, net, avail)
                    )
            continue
        if stage == n_stages:
            msgs = tuple(
                (K_STOP, c, s, 0, 0, 0) for s in range(cfg.servers)
            )
            cl2 = (stage + 1, False, att, 0, frozenset())
            out.append((_set(clients, c, cl2), servers, net + msgs, avail))
        elif cfg.script[stage % steps] == "fetch":
            att2 = att + 1
            reqs = [(K_REQ, c, s, att2, 0, 0) for s in range(cfg.servers)]
            cl2 = (
                stage, True, att2, 0, frozenset(range(cfg.servers))
            )
            for added, av2 in _variants(reqs, avail, cfg.kinds, points):
                out.append((_set(clients, c, cl2), servers, net + added,
                            av2))
        else:  # push
            seq = stage // steps + 1
            msgs = [(K_PUSH, c, s, seq, 0, 0) for s in range(cfg.servers)]
            cl2 = (stage + 1, False, att, 0, frozenset())
            for added, av2 in _variants(msgs, avail, cfg.kinds, points):
                out.append((_set(clients, c, cl2), servers, net + added,
                            av2))
    return out


def _successors_elastic(state, sem, cfg, viol, points) -> list:
    """Elastic-mode successor relation: the base protocol moves (with
    incarnation-aware pushes) plus three membership transitions —
    server SNAPSHOT (persist applied+window, once), server CRASH-RESTORE
    (roll back to the snapshot, or to nothing; spends the elastic fault
    budget), and client REPLACE (preempt + respawn from step 0 with a
    fresh incarnation; spends the same budget)."""
    clients, servers, net, avail, eavail = state
    out = []
    deliv = _deliverable(net)
    steps = len(cfg.script)
    n_stages = cfg.rounds * steps
    all_clients = frozenset(range(cfg.clients))

    # -- server deliveries (handle + reply are one atomic step)
    for i in deliv:
        m = net[i]
        kind = m[0]
        if kind == K_REP:
            continue
        s = m[2]
        stops = servers[s][0]
        if stops == all_clients:
            continue  # server exited its loop; late messages park
        rest = net[:i] + net[i + 1:]
        if kind == K_REQ:
            c, att = m[1], m[3]
            echo = att if sem.attempt_echoed else -1
            rep = (K_REP, s, c, att, echo, 0)
            for added, av2 in _variants([rep], avail, cfg.kinds, points):
                out.append((clients, servers, rest + added, av2, eavail))
        elif kind == K_PUSH:
            srv2 = _apply_push_elastic(
                servers, s, m[1], m[3], m[4], sem, cfg, viol
            )
            out.append((clients, srv2, rest, avail, eavail))
        else:  # STOP
            srv2 = _set(
                servers, s, (stops | {m[1]},) + servers[s][1:]
            )
            out.append((clients, srv2, rest, avail, eavail))

    # -- membership transitions
    for s, sv in enumerate(servers):
        stops, applied, dedup, snap = sv
        if stops == all_clients:
            continue  # server done — nothing left to snapshot or lose
        if snap is None and sem.snapshot_includes_dedup is not None:
            # take THE shard snapshot (once per run keeps the state
            # space tight; one snapshot point is enough to exhibit any
            # snapshot-consistency bug)
            snap2 = (
                applied,
                dedup if sem.snapshot_includes_dedup else None,
            )
            out.append((
                clients, _set(servers, s, (stops, applied, dedup, snap2)),
                net, avail, eavail,
            ))
        if eavail:
            # crash + restore: everything since the snapshot (or since
            # boot) rolls back TOGETHER — applied-and-unpersisted pushes
            # disappear from `applied` because the center they mutated
            # rolled back with them, so their redelivery re-applying is
            # correct, not a double-apply. The membership view (stops)
            # is in the snapshot, so it survives.
            if snap is not None:
                r_applied, r_dedup = snap
                if r_dedup is None:
                    r_dedup = _fresh_dedup(cfg)
            else:
                r_applied, r_dedup = frozenset(), _fresh_dedup(cfg)
            out.append((
                clients,
                _set(servers, s, (stops, r_applied, r_dedup, snap)),
                net, avail, False,
            ))
    if eavail:
        for c, cl in enumerate(clients):
            if cl[0] > n_stages:
                continue  # already done — nothing left to preempt
            # REPLACE: the process is killed and respawned — it re-runs
            # from step 0 (seq numbering restarts) under a fresh
            # incarnation; attempt ids keep counting (epoch-seeded
            # disjointness in the implementation)
            cl2 = (0, False, cl[2], 0, frozenset(), cl[5] + 1)
            out.append(
                (_set(clients, c, cl2), servers, net, avail, False)
            )

    # -- client moves
    for c, cl in enumerate(clients):
        stage, waiting, att, retries, pending, inc = cl
        if stage > n_stages:
            continue  # done
        if waiting:
            for i in deliv:
                m = net[i]
                if m[0] != K_REP or m[2] != c:
                    continue
                rest = net[:i] + net[i + 1:]
                true_att, s = m[3], m[1]
                if true_att != att:
                    if sem.attempt_echoed and sem.attempt_checked:
                        # stale reply detected and dropped (consumed)
                        out.append(
                            (clients, servers, rest, avail, eavail)
                        )
                        continue
                    viol.setdefault(
                        "MPT011",
                        f"[{cfg.label}] client {c} assembled a reply "
                        f"generated for attempt {true_att} into its live "
                        f"attempt {att} — "
                        + (
                            "the echoed attempt id is never compared "
                            "to the live one"
                            if sem.attempt_echoed
                            else "replies carry no attempt id, so stale "
                            "ones are indistinguishable from fresh"
                        ),
                    )
                pend2 = pending - {s}
                if pend2:
                    cl2 = (stage, True, att, retries, pend2, inc)
                else:
                    cl2 = (stage + 1, False, att, 0, frozenset(), inc)
                out.append(
                    (_set(clients, c, cl2), servers, rest, avail, eavail)
                )
            if sem.reply_recv_timeout and _starved(
                net, c, att, pending, sem
            ):
                if retries < cfg.max_retries:
                    att2 = att + 1
                    reqs = [
                        (K_REQ, c, s, att2, 0, 0) for s in sorted(pending)
                    ]
                    cl2 = (stage, True, att2, retries + 1, pending, inc)
                    for added, av2 in _variants(
                        reqs, avail, cfg.kinds, points
                    ):
                        out.append((
                            _set(clients, c, cl2), servers, net + added,
                            av2, eavail,
                        ))
                else:
                    # retries exhausted: skip the round (the ps_roles
                    # graceful-degradation path), resume next round
                    stage2 = (stage // steps + 1) * steps
                    cl2 = (stage2, False, att, 0, frozenset(), inc)
                    out.append(
                        (_set(clients, c, cl2), servers, net, avail,
                         eavail)
                    )
            continue
        if stage == n_stages:
            msgs = tuple(
                (K_STOP, c, s, 0, 0, 0) for s in range(cfg.servers)
            )
            cl2 = (stage + 1, False, att, 0, frozenset(), inc)
            out.append(
                (_set(clients, c, cl2), servers, net + msgs, avail,
                 eavail)
            )
        elif cfg.script[stage % steps] == "fetch":
            att2 = att + 1
            reqs = [(K_REQ, c, s, att2, 0, 0) for s in range(cfg.servers)]
            cl2 = (
                stage, True, att2, 0, frozenset(range(cfg.servers)), inc
            )
            for added, av2 in _variants(reqs, avail, cfg.kinds, points):
                out.append((
                    _set(clients, c, cl2), servers, net + added, av2,
                    eavail,
                ))
        else:  # push
            seq = stage // steps + 1
            msgs = [
                (K_PUSH, c, s, seq, inc, 0) for s in range(cfg.servers)
            ]
            cl2 = (stage + 1, False, att, 0, frozenset(), inc)
            for added, av2 in _variants(msgs, avail, cfg.kinds, points):
                out.append((
                    _set(clients, c, cl2), servers, net + added, av2,
                    eavail,
                ))
    return out


# sharded mode (cfg.sharded) reshapes the elastic state:
# state  = (clients, servers, net, fault_avail, crash_avail,
#           handoff_avail, owners)
#          owners[h] = server index currently owning shard h; HANDOFF
#          moves one shard to another server (own one-shot budget)
# server = (stops, applied, dedup) — applied keyed (c, inc, shard,
#          seq); dedup is a sorted tuple-map of ((c, inc, shard) ->
#          (high, seen)) windows, created lazily — per-shard windows
#          travel with the shard on handoff (or are forgotten, the
#          seeded handoff_carries_dedup=False bug). CRASH restores from
#          NOTHING: snapshot-at-any-point timing multiplies the state
#          space ~8x and its consistency hazard is already exhausted by
#          elastic_config, so this config keeps only the restart — the
#          shard data (and thus `applied`) rolls back with the center,
#          which is exactly the real restore's semantics for shards the
#          snapshot predates
# PUSH   = (K_PUSH, c, dst, seq, (inc, shard), flags) — one per shard,
#          addressed to the owner AT SEND time but applied by the owner
#          AT DELIVERY time (the client-side reshard repair re-routes
#          in-flight traffic; dst only keys the FIFO stream)
# client REPLACE is disabled here (elastic_config owns that hazard)


def _dmap_get(dmap, key):
    for k, v in dmap:
        if k == key:
            return v
    return (0, frozenset())


def _dmap_set(dmap, key, val) -> tuple:
    out = [kv for kv in dmap if kv[0] != key]
    out.append((key, val))
    out.sort(key=lambda kv: kv[0])
    return tuple(out)


def _apply_push_sharded(servers, s, c, seq, inc, h, sem, cfg, viol):
    """Sharded push application at shard ``h``'s current owner ``s``:
    the admit window is selected per (client, incarnation, shard) — the
    model twin of the implementation's one-admit-per-envelope dedup
    surviving shard collapse — and the exactly-once assertion keys the
    applied set the same way."""
    stops, applied, dedup = servers[s]
    keyed = sem.dedup_keyed_by_epoch
    widx = inc if keyed else 0
    akey = (c, inc, h, seq)
    ds = dedup
    if sem.dedup is not None:
        high, seen = _dmap_get(dedup, (c, widx, h))
        bound = high - cfg.window
        if sem.dedup.rejects_at_boundary:
            reject = seq <= bound
        else:
            reject = seq < bound
        if not reject and sem.dedup.checks_seen and seq in seen:
            reject = True
        admitted = not reject
        if admitted:
            seen2 = seen | {seq}
            if seq > high:
                if sem.dedup.prunes_seen and len(seen2) > cfg.window:
                    floor = seq - cfg.window
                    seen2 = frozenset(x for x in seen2 if x > floor)
                ds = _dmap_set(dedup, (c, widx, h), (seq, frozenset(seen2)))
            else:
                ds = _dmap_set(dedup, (c, widx, h), (high, frozenset(seen2)))
    elif sem.dedup_opaque:
        admitted = akey not in applied
    else:
        admitted = True
    if admitted:
        if akey in applied:
            viol.setdefault(
                "MPT009",
                f"[{cfg.label}] push (client {c}, shard {h}, seq {seq}) "
                "applied TWICE: a redelivered copy passed the dedup admit "
                "at the shard's new owner because the handoff shipped the "
                "shard data without its dedup window",
            )
        applied = applied | {akey}
    elif (
        sem.dedup is not None
        and not keyed
        and akey not in applied
        and any(
            t[0] == c and t[2] == h and t[3] == seq and t[1] != inc
            for t in applied
        )
    ):
        viol.setdefault(
            "MPT009",
            f"[{cfg.label}] push (client {c}, incarnation {inc}, shard "
            f"{h}, seq {seq}) wrongfully REJECTED: the dedup window is "
            "not keyed by client epoch, so the replacement's push was "
            "mistaken for its predecessor's replay and dropped",
        )
    return _set(servers, s, (stops, applied, ds))


def _successors_sharded(state, sem, cfg, viol, points) -> list:
    """Sharded-mode successor relation: the elastic protocol moves (with
    delivery-time push re-routing to the shard's current owner) plus the
    HANDOFF transition — one shard's ownership moves to another server,
    carrying its applied entries (the shard data embodies them) and,
    per the extracted ``handoff_carries_dedup``, its dedup windows."""
    clients, servers, net, avail, eavail, havail, owners = state
    out = []
    deliv = _deliverable(net)
    steps = len(cfg.script)
    n_stages = cfg.rounds * steps
    all_clients = frozenset(range(cfg.clients))

    def _send_variants(msgs, av):
        if cfg.fault_push_only and not any(m[0] == K_PUSH for m in msgs):
            return [(tuple(msgs), av)]
        return _variants(msgs, av, cfg.kinds, points)

    # -- server deliveries (handle + reply are one atomic step)
    for i in deliv:
        m = net[i]
        kind = m[0]
        if kind == K_REP:
            continue
        rest = net[:i] + net[i + 1:]
        if kind == K_PUSH:
            inc, h = m[4]
            tgt = owners[h]  # re-routed to the CURRENT owner
            if servers[tgt][0] == all_clients:
                continue  # owner exited its loop; late pushes park
            srv2 = _apply_push_sharded(
                servers, tgt, m[1], m[3], inc, h, sem, cfg, viol
            )
            out.append(
                (clients, srv2, rest, avail, eavail, havail, owners)
            )
            continue
        s = m[2]
        stops = servers[s][0]
        if stops == all_clients:
            continue  # server exited its loop; late messages park
        if kind == K_REQ:
            c, att = m[1], m[3]
            echo = att if sem.attempt_echoed else -1
            rep = (K_REP, s, c, att, echo, 0)
            for added, av2 in _send_variants([rep], avail):
                out.append(
                    (clients, servers, rest + added, av2, eavail,
                     havail, owners)
                )
        else:  # STOP
            srv2 = _set(servers, s, (stops | {m[1]},) + servers[s][1:])
            out.append(
                (clients, srv2, rest, avail, eavail, havail, owners)
            )

    # -- handoff: one shard's ownership moves to another live server
    if havail:
        for h, owner in enumerate(owners):
            o_stops, o_applied, o_dedup = servers[owner]
            if o_stops == all_clients:
                continue  # old owner already exited — nothing to hand off
            for s2 in range(cfg.servers):
                if s2 == owner or servers[s2][0] == all_clients:
                    continue
                moved = frozenset(t for t in o_applied if t[2] == h)
                moved_d = tuple(
                    kv for kv in o_dedup if kv[0][2] == h
                )
                kept_d = tuple(kv for kv in o_dedup if kv[0][2] != h)
                d_stops, d_applied, d_dedup = servers[s2]
                if sem.handoff_carries_dedup is False:
                    nd = d_dedup  # the window is forgotten in transit
                else:
                    nd = d_dedup
                    for k, v in moved_d:
                        nd = _dmap_set(nd, k, v)
                srv2 = _set(
                    servers, owner, (o_stops, o_applied - moved, kept_d)
                )
                srv2 = _set(
                    srv2, s2, (d_stops, d_applied | moved, nd)
                )
                out.append((
                    clients, srv2, net, avail, eavail, False,
                    _set(owners, h, s2),
                ))

    # -- crash-restore (restart-from-nothing; REPLACE and snapshot
    # timing are elastic_config's jurisdiction — see the shape comment)
    if eavail:
        for s, sv in enumerate(servers):
            stops = sv[0]
            if stops == all_clients:
                continue
            out.append((
                clients,
                _set(servers, s, (stops, frozenset(), ())),
                net, avail, False, havail, owners,
            ))

    # -- client moves
    for c, cl in enumerate(clients):
        stage, waiting, att, retries, pending, inc = cl
        if stage > n_stages:
            continue  # done
        if waiting:
            for i in deliv:
                m = net[i]
                if m[0] != K_REP or m[2] != c:
                    continue
                rest = net[:i] + net[i + 1:]
                true_att, s = m[3], m[1]
                if true_att != att:
                    if sem.attempt_echoed and sem.attempt_checked:
                        out.append(
                            (clients, servers, rest, avail, eavail,
                             havail, owners)
                        )
                        continue
                    viol.setdefault(
                        "MPT011",
                        f"[{cfg.label}] client {c} assembled a reply "
                        f"generated for attempt {true_att} into its live "
                        f"attempt {att} — "
                        + (
                            "the echoed attempt id is never compared "
                            "to the live one"
                            if sem.attempt_echoed
                            else "replies carry no attempt id, so stale "
                            "ones are indistinguishable from fresh"
                        ),
                    )
                pend2 = pending - {s}
                if pend2:
                    cl2 = (stage, True, att, retries, pend2, inc)
                else:
                    cl2 = (stage + 1, False, att, 0, frozenset(), inc)
                out.append((
                    _set(clients, c, cl2), servers, rest, avail, eavail,
                    havail, owners,
                ))
            if sem.reply_recv_timeout and _starved(
                net, c, att, pending, sem
            ):
                if retries < cfg.max_retries:
                    att2 = att + 1
                    reqs = [
                        (K_REQ, c, s, att2, 0, 0) for s in sorted(pending)
                    ]
                    cl2 = (stage, True, att2, retries + 1, pending, inc)
                    for added, av2 in _send_variants(reqs, avail):
                        out.append((
                            _set(clients, c, cl2), servers, net + added,
                            av2, eavail, havail, owners,
                        ))
                else:
                    stage2 = (stage // steps + 1) * steps
                    cl2 = (stage2, False, att, 0, frozenset(), inc)
                    out.append((
                        _set(clients, c, cl2), servers, net, avail,
                        eavail, havail, owners,
                    ))
            continue
        if stage == n_stages:
            msgs = tuple(
                (K_STOP, c, s, 0, 0, 0) for s in range(cfg.servers)
            )
            cl2 = (stage + 1, False, att, 0, frozenset(), inc)
            out.append((
                _set(clients, c, cl2), servers, net + msgs, avail,
                eavail, havail, owners,
            ))
        elif cfg.script[stage % steps] == "fetch":
            att2 = att + 1
            reqs = [(K_REQ, c, s, att2, 0, 0) for s in range(cfg.servers)]
            cl2 = (
                stage, True, att2, 0, frozenset(range(cfg.servers)), inc
            )
            for added, av2 in _send_variants(reqs, avail):
                out.append((
                    _set(clients, c, cl2), servers, net + added, av2,
                    eavail, havail, owners,
                ))
        else:  # push: one message per shard, addressed by current view
            seq = stage // steps + 1
            msgs = [
                (K_PUSH, c, owners[h], seq, (inc, h), 0)
                for h in range(cfg.shards)
            ]
            cl2 = (stage + 1, False, att, 0, frozenset(), inc)
            for added, av2 in _send_variants(msgs, avail):
                out.append((
                    _set(clients, c, cl2), servers, net + added, av2,
                    eavail, havail, owners,
                ))
    return out


def _terminal(state, cfg) -> bool:
    clients, servers = state[0], state[1]
    n_stages = cfg.rounds * len(cfg.script)
    all_clients = frozenset(range(cfg.clients))
    return all(cl[0] > n_stages for cl in clients) and all(
        sv[0] == all_clients for sv in servers
    )


def _describe_stuck(state, cfg) -> str:
    clients, servers, net = state[0], state[1], state[2]
    blocked = [
        f"client {c} waiting on server(s) {sorted(cl[4])} "
        f"(attempt {cl[2]})"
        for c, cl in enumerate(clients)
        if cl[1]
    ]
    waiting_servers = [
        f"server {s} missing STOP from {sorted(frozenset(range(cfg.clients)) - sv[0])}"
        for s, sv in enumerate(servers)
        if sv[0] != frozenset(range(cfg.clients))
    ]
    inflight = ", ".join(
        f"{_KIND_LABEL[m[0]]} {m[1]}->{m[2]}" for m in net
    ) or "none"
    return (
        f"[{cfg.label}] reachable state where nothing can move: "
        + "; ".join(blocked + waiting_servers)
        + f" (in flight: {inflight})"
    )


def check(sem: ModelSemantics, cfg: Optional[ModelConfig] = None
          ) -> CheckResult:
    """Exhaustively explore one configuration. Every violation dict entry
    carries its first witness; ``states`` is the visited-set size (the
    exhaustiveness receipt the CLI prints)."""
    cfg = cfg or ModelConfig()
    if cfg.sharded:
        clients0 = tuple(
            (0, False, 0, 0, frozenset(), 0) for _ in range(cfg.clients)
        )
        servers0 = tuple(
            (frozenset(), frozenset(), ()) for _ in range(cfg.servers)
        )
        owners0 = tuple(h % cfg.servers for h in range(cfg.shards))
        init = (clients0, servers0, (), True, True, True, owners0)
        succ_fn = _successors_sharded
    elif cfg.elastic:
        clients0 = tuple(
            (0, False, 0, 0, frozenset(), 0) for _ in range(cfg.clients)
        )
        servers0 = tuple(
            (frozenset(), frozenset(), _fresh_dedup(cfg), None)
            for _ in range(cfg.servers)
        )
        init = (clients0, servers0, (), True, True)
        succ_fn = _successors_elastic
    else:
        clients0 = tuple(
            (0, False, 0, 0, frozenset()) for _ in range(cfg.clients)
        )
        servers0 = tuple(
            (
                frozenset(),
                frozenset(),
                tuple((0, frozenset()) for _ in range(cfg.clients)),
            )
            for _ in range(cfg.servers)
        )
        init = (clients0, servers0, (), True)
        succ_fn = _successors
    visited = {init}
    stack = [init]
    viol: dict = {}
    points: set = set()
    truncated = False
    while stack:
        if viol:
            # a witness is in hand — further exploration can only find
            # MORE schedules for the same (first-witness) verdict, so a
            # failing run stops here (a CLEAN run is unaffected: it
            # explores to fixpoint, which is what `states` certifies)
            break
        st = stack.pop()
        succ = succ_fn(st, sem, cfg, viol, points)
        if not succ:
            if not _terminal(st, cfg):
                viol.setdefault("MPT010", _describe_stuck(st, cfg))
            continue
        for s2 in succ:
            s2 = s2[:2] + (_canon(s2[2]),) + s2[3:]
            if s2 in visited:
                continue
            if len(visited) >= cfg.max_states:
                truncated = True
                continue
            visited.add(s2)
            stack.append(s2)
    return CheckResult(
        config=cfg,
        states=len(visited),
        fault_points=len(points),
        violations=viol,
        truncated=truncated,
    )


def check_all(sem: ModelSemantics, configs=None, quick: bool = False) -> list:
    """One CheckResult per configuration (default: the acceptance pair,
    plus the elastic-membership configuration when the protocol has the
    machinery it exercises — an epoch-keyed dedup window or shard
    snapshot persistence; a bare dedup'd protocol with neither would
    fail elastic schedules it never claims to survive). ``quick`` swaps
    the default and sharded configurations for their 1-client lint-tier
    variants (see :func:`default_configs` / :func:`sharded_config`; the
    elastic configuration is already 1-client)."""
    if configs is None:
        configs = default_configs(sem.has_push, quick)
        if sem.dedup is not None and (
            sem.dedup_keyed_by_epoch
            or sem.snapshot_includes_dedup is not None
        ):
            configs = tuple(configs) + (elastic_config(),)
        if (
            sem.dedup is not None
            and sem.handoff_carries_dedup is not None
        ):
            # the protocol has shard-handoff machinery: verify
            # exactly-once across ownership moves too
            configs = tuple(configs) + (sharded_config(quick),)
    return [check(sem, cfg) for cfg in configs]


# ---------------------------------------------------------------------------
# the serving-fleet routing model (MPT019)
#
# A different conversation from the PS pair, so a different model: one
# router admits R requests and routes each to one of S replicas; a
# replica that receives a ROUTE answers with a REPLY; the single fault
# is a replica KILL (at most one, never the last replica standing),
# which silently discards every message to or from the dead rank —
# including a consumed-but-unreplied request, the orphan the redispatch
# path exists for. The property checked is the soak gate's invariant in
# model form: **no admitted request is both lost and unacked** — every
# routed request reaches finished in every schedule, with the kill
# allowed anywhere. Recovery requires BOTH extracted facts: a
# redispatch send path (``redispatch_on_death``) and a timeout on the
# router's reply recv (``reply_recv_timeout`` — a router blocked forever
# on a dead replica's reply never reaches its redispatch code).
#
# state = (reqs, alive, net, kill_available)
#   req   = (status, assignee)   status 0 unrouted / 1 routed / 2 done;
#           assignee = replica rank (model index), -1 while unrouted
#   alive = tuple of bools per replica
#   msg   = the shared 6-tuple shape: (K_REQ, -1, s, rid, 0, 0) for
#           ROUTE, (K_REP, s, -1, rid, 0, 0) for REPLY (router = -1) —
#           _canon/_deliverable apply unchanged
#
# The weight lanes (13/14) and STOP are not modeled: they carry no
# request-lifecycle obligation (installs are idempotent, teardown is
# never faulted — same stance as the PS model's STOP).


@dataclasses.dataclass(frozen=True)
class FleetModelSemantics:
    """The two extracted facts the fleet model branches on."""

    redispatch_on_death: bool = True
    reply_timeout: bool = True

    @property
    def can_recover(self) -> bool:
        return self.redispatch_on_death and self.reply_timeout


def fleet_from_protocol(fsem) -> FleetModelSemantics:
    """FleetModelSemantics from a ``protocol.FleetSemantics``."""
    return FleetModelSemantics(
        redispatch_on_death=fsem.redispatch_on_death,
        reply_timeout=fsem.reply_recv_timeout,
    )


def fleet_config(quick: bool = False) -> ModelConfig:
    """The fleet acceptance configuration: 1 router x 2 replicas (the
    minimum where a kill leaves a survivor to redispatch to), 3 requests
    (2 quick) — enough that the kill can land before, between and after
    routes. ``script``/``window``/``kinds`` are unused by the fleet
    explorer; ``rounds`` counts requests."""
    return ModelConfig(
        algo="fleet-route",
        script=("route",),
        clients=1,
        servers=2,
        rounds=2 if quick else 3,
        kinds=(),
    )


def _fleet_terminal(state) -> bool:
    return all(r[0] == 2 for r in state[0])


def _fleet_successors(state, fsem, cfg, viol, points):
    reqs, alive, net, kill_avail = state
    out = []
    # admit+route the next unrouted request (admission order) to each
    # live replica — the policy is nondeterministic here; every policy's
    # choice is some schedule
    for rid, (status, _a) in enumerate(reqs):
        if status == 0:
            for s, up in enumerate(alive):
                if up:
                    out.append((
                        _set(reqs, rid, (1, s)),
                        alive,
                        net + ((K_REQ, -1, s, rid, 0, 0),),
                        kill_avail,
                    ))
            break
    # deliveries
    for i in _deliverable(net):
        m = net[i]
        rest = net[:i] + net[i + 1:]
        kind, rid = m[0], m[3]
        if kind == K_REQ:
            s = m[2]
            if not alive[s]:  # raced a kill; the filter owns this
                out.append((reqs, alive, rest, kill_avail))
            else:  # replica consumes the route, its reply takes wing
                out.append((
                    reqs, alive,
                    rest + ((K_REP, s, -1, rid, 0, 0),),
                    kill_avail,
                ))
        elif kind == K_REP:
            status, assignee = reqs[rid]
            if status == 1 and assignee == m[1]:
                out.append((
                    _set(reqs, rid, (2, assignee)), alive, rest,
                    kill_avail,
                ))
            else:  # a redispatched rid's late original reply: dropped
                out.append((reqs, alive, rest, kill_avail))
    # the kill fault: one replica, never the last one standing; every
    # message to or from the dead rank dies with it (a consumed-but-
    # unreplied request becomes an orphan via its discarded REPLY)
    if kill_avail and sum(alive) >= 2:
        for s, up in enumerate(alive):
            if up:
                points.add(("kill", (s,)))
                out.append((
                    reqs,
                    _set(alive, s, False),
                    tuple(m for m in net if m[1] != s and m[2] != s),
                    False,
                ))
    # orphan recovery: the router's detect-timeout fires and the
    # redispatch path re-routes each dead-assigned request — only when
    # the implementation has both halves of that path
    if fsem.can_recover:
        for rid, (status, assignee) in enumerate(reqs):
            if status == 1 and assignee >= 0 and not alive[assignee]:
                for s, up in enumerate(alive):
                    if up:
                        out.append((
                            _set(reqs, rid, (1, s)),
                            alive,
                            net + ((K_REQ, -1, s, rid, 0, 0),),
                            kill_avail,
                        ))
    return out


def _fleet_describe_stuck(state, cfg) -> str:
    reqs, alive = state[0], state[1]
    lost = [
        f"request {rid} routed to dead replica {assignee}"
        for rid, (status, assignee) in enumerate(reqs)
        if status == 1 and assignee >= 0 and not alive[assignee]
    ]
    return (
        f"[{cfg.label}] a replica kill strands "
        + "; ".join(lost)
        + " with no recovery path — the request is lost but was never "
        "shed or nacked (redispatch-on-death + reply-recv timeout are "
        "the two halves the router needs)"
    )


def check_fleet(fsem: FleetModelSemantics,
                cfg: Optional[ModelConfig] = None) -> CheckResult:
    """Exhaustively explore the fleet-route configuration. A reachable
    state where nothing can move and some routed request is unfinished
    is the MPT019 violation (request lost under a single replica
    kill)."""
    cfg = cfg or fleet_config()
    init = (
        tuple((0, -1) for _ in range(cfg.rounds)),
        tuple(True for _ in range(cfg.servers)),
        (),
        True,
    )
    visited = {init}
    stack = [init]
    viol: dict = {}
    points: set = set()
    truncated = False
    while stack:
        if viol:
            break  # first witness wins, same stance as check()
        st = stack.pop()
        succ = _fleet_successors(st, fsem, cfg, viol, points)
        if not succ:
            if not _fleet_terminal(st):
                viol.setdefault(
                    "MPT019", _fleet_describe_stuck(st, cfg)
                )
            continue
        for s2 in succ:
            s2 = s2[:2] + (_canon(s2[2]),) + s2[3:]
            if s2 in visited:
                continue
            if len(visited) >= cfg.max_states:
                truncated = True
                continue
            visited.add(s2)
            stack.append(s2)
    return CheckResult(
        config=cfg,
        states=len(visited),
        fault_points=len(points),
        violations=viol,
        truncated=truncated,
    )
