"""Static pass of the distributed-correctness linter.

Drives the AST rules in :mod:`mpit_tpu.analysis.rules` over a file set,
applies inline suppressions and the checked-in baseline, and returns
:class:`~mpit_tpu.analysis.findings.Finding` lists. The analysis modules
are stdlib-only: scanned code is parsed, never imported, and no jax
BACKEND is ever initialized (the parent package's import does pull in the
jax module for its compat shims, but linting touches no devices) — safe
for pre-commit hooks and bare CI containers.

Suppression layers, outermost first:

1. baseline file (``analysis-baseline.json`` at the repo root): accepted
   deviations, counted per fingerprint — the build fails only on NEW
   findings (see :func:`mpit_tpu.analysis.findings.new_findings`);
2. inline ``# mpit-analysis: ignore[MPT005]`` (or bare ``ignore`` for all
   rules) on the flagged line;
3. barrier functions: a def annotated ``# mpit-analysis: host-sync-barrier``
   (see ``utils/profiling.force_completion``) is exempt from the host-sync
   rule, body and call sites both.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from pathlib import Path
from typing import Iterable, Optional, Sequence

from mpit_tpu.analysis import astutil
from mpit_tpu.analysis.findings import Finding

_IGNORE_RE = re.compile(
    r"#\s*mpit-analysis:\s*ignore(?:\[([A-Z0-9,\s]+)\])?"
)
_BARRIER_RE = re.compile(r"#\s*mpit-analysis:\s*host-sync-barrier")

BASELINE_FILENAME = "analysis-baseline.json"


@dataclasses.dataclass
class Config:
    """Knobs the rules read. Defaults describe THIS repo; tests override
    (e.g. ``hot_all=True`` to lint a fixture as if it were a hot path)."""

    # path components marking latency-critical modules for the host-sync
    # rule (run.py, parallel/, ops/ — ISSUE 1 hot-path set)
    hot_parts: Sequence[str] = ("parallel", "ops")
    hot_basenames: Sequence[str] = ("run.py",)
    hot_all: bool = False  # treat every scanned file as hot (fixtures)
    # functions whose calls/bodies are sanctioned host syncs, on top of the
    # `# mpit-analysis: host-sync-barrier` markers discovered in sources
    host_sync_barriers: Sequence[str] = ("force_completion",)
    # include mpit_tpu/parallel's TAG_* registry even when linting a path
    # that doesn't contain it (cross-module collisions against the
    # canonical protocol tags)
    canonical_tag_registry: bool = True
    # path components marking transport-boundary modules for the pickle
    # wire-format rule (modules may also opt in with a
    # `# mpit-analysis: wire-boundary` marker comment)
    wire_parts: Sequence[str] = ("transport", "native")
    # the canonical wire pickle-protocol constant: its name, and an
    # optional value override for tests (default: extracted from
    # transport/socket_transport.py — scan set first, installed package
    # as fallback; never imported)
    wire_protocol_name: str = "WIRE_PICKLE_PROTOCOL"
    wire_pickle_protocol: Optional[int] = None
    # the canonical binary-frame version constant: its name, and an
    # optional value override for tests (default: extracted from
    # transport/wire.py the same way — scan set first, installed package
    # as fallback; never imported)
    wire_version_name: str = "WIRE_FORMAT_VERSION"
    wire_format_version: Optional[int] = None
    # restrict the run to these rule ids (``--only MPT013,MPT014``); None
    # runs everything. Rule modules owning no selected id are skipped
    # entirely, so one rule can be iterated without the full-pass cost
    only_rules: Optional[Sequence[str]] = None


@dataclasses.dataclass
class ModuleCtx:
    path: Path  # absolute
    rel: str  # posix, relative to the scan root
    tree: ast.Module
    source_lines: list
    parents: dict
    nodes: list  # flat ast.walk order — rules iterate this, never re-walk
    ignores: dict  # line -> set of rule ids, or {"*"}
    barrier_defs: set  # function names marked host-sync-barrier

    def is_hot(self, config: Config) -> bool:
        if config.hot_all:
            return True
        parts = Path(self.rel).parts
        return (
            parts[-1] in config.hot_basenames
            or any(p in config.hot_parts for p in parts[:-1])
        )

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.rel,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            symbol=astutil.enclosing_symbol(node, self.parents),
            message=message,
            text=astutil.line_text(self.source_lines, node),
        )


@dataclasses.dataclass
class Project:
    modules: list  # list[ModuleCtx]
    config: Config
    # lazily-built cross-module name-resolution index (analysis/graph.py);
    # per-file rules never touch it, cross-module rules share one build
    _graph: object = dataclasses.field(default=None, repr=False)
    # lazily-extracted role models (analysis/protocol.py) — the protocol
    # rules, the model check, and conformance all need the same extraction
    _roles: object = dataclasses.field(default=None, repr=False)
    # lazily-built whole-program concurrency model (analysis/threads.py) —
    # the MPT013-015 rules and the `threads` CLI share one build
    _threads: object = dataclasses.field(default=None, repr=False)
    # lazily-built wire payload-schema model (analysis/schema.py) — the
    # MPT016-018 rules and the `schema` CLI/lockfile share one build
    _schema: object = dataclasses.field(default=None, repr=False)
    # lazily-built precision-dataflow model (analysis/numerics.py) — the
    # MPT020-022 rules and the `numerics` CLI share one build
    _numerics: object = dataclasses.field(default=None, repr=False)

    @property
    def graph(self):
        if self._graph is None:
            from mpit_tpu.analysis import graph as graph_mod

            self._graph = graph_mod.ModuleGraph(self.modules)
        return self._graph

    @property
    def roles(self):
        if self._roles is None:
            from mpit_tpu.analysis import protocol

            self._roles = protocol.extract_roles(self)
        return self._roles

    @property
    def threads(self):
        if self._threads is None:
            from mpit_tpu.analysis import threads as threads_mod

            self._threads = threads_mod.build_model(self)
        return self._threads

    @property
    def numerics(self):
        if self._numerics is None:
            from mpit_tpu.analysis import numerics as numerics_mod

            self._numerics = numerics_mod.build_model(self)
        return self._numerics

    @property
    def schema(self):
        if self._schema is None:
            from mpit_tpu.analysis import schema as schema_mod

            self._schema = schema_mod.build_schema(self)
        return self._schema


def _parse_ignores(source_lines: list) -> dict:
    out: dict = {}
    for i, line in enumerate(source_lines, start=1):
        m = _IGNORE_RE.search(line)
        if not m:
            continue
        if m.group(1):
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        else:
            out[i] = {"*"}
    return out


def _parse_barriers(nodes: list, source_lines: list) -> set:
    """Function names whose def line (or the line above it) carries the
    host-sync-barrier marker."""
    out = set()
    for node in nodes:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for ln in (node.lineno, node.lineno - 1):
            if 1 <= ln <= len(source_lines) and _BARRIER_RE.search(
                source_lines[ln - 1]
            ):
                out.add(node.name)
                break
    return out


def load_module(path: Path, rel: str) -> Optional[ModuleCtx]:
    try:
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError):
        return None  # unreadable / non-parse files are out of scope
    lines = source.splitlines()
    nodes, parents = astutil.walk_and_parents(tree)
    return ModuleCtx(
        path=path,
        rel=rel,
        tree=tree,
        source_lines=lines,
        parents=parents,
        nodes=nodes,
        ignores=_parse_ignores(lines),
        barrier_defs=_parse_barriers(nodes, lines),
    )


def collect_files(paths: Iterable) -> list:
    """(abs_path, rel) pairs for every .py under ``paths`` (files pass
    through; directories recurse, skipping __pycache__/hidden dirs)."""
    out = []
    for p in paths:
        p = Path(p)
        if p.is_file():
            out.append((p.resolve(), p.name))
            continue
        root = p.resolve()
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d
                for d in sorted(dirnames)
                if d != "__pycache__" and not d.startswith(".")
            ]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    ap = Path(dirpath) / fn
                    out.append((ap, ap.relative_to(root.parent).as_posix()))
    return out


def run_lint(
    paths: Iterable, config: Optional[Config] = None
) -> list:
    """Lint ``paths`` (files and/or directories) and return the suppressed,
    sorted finding list (baseline NOT applied — that's the caller's
    policy decision; see :func:`mpit_tpu.analysis.findings.new_findings`)."""
    from mpit_tpu.analysis import rules

    config = config or Config()
    modules = []
    for ap, rel in collect_files(paths):
        ctx = load_module(ap, rel)
        if ctx is not None:
            modules.append(ctx)
    project = Project(modules=modules, config=config)
    only = set(config.only_rules) if config.only_rules else None
    findings = []
    for rule_mod in rules.RULE_MODULES:
        if only is not None and not only & set(rule_mod.RULES):
            continue
        findings.extend(rule_mod.run(project))
    findings = [
        f
        for f in findings
        if not _suppressed(f, {m.rel: m for m in modules})
        and (only is None or f.rule in only)
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _suppressed(f: Finding, by_rel: dict) -> bool:
    mod = by_rel.get(f.path)
    if mod is None:
        return False
    ignored = mod.ignores.get(f.line, ())
    return "*" in ignored or f.rule in ignored


def find_repo_root(start: Path) -> Optional[Path]:
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for candidate in (cur, *cur.parents):
        if (candidate / "pyproject.toml").exists():
            return candidate
    return None


def default_baseline_path(scan_path) -> Optional[Path]:
    env = os.environ.get("MPIT_ANALYSIS_BASELINE")
    if env:
        return Path(env)
    root = find_repo_root(Path(scan_path))
    return root / BASELINE_FILENAME if root is not None else None
