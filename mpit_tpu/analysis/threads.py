"""Whole-program concurrency model for the static linter (stdlib-only).

Built on the PR-2 module graph (:mod:`mpit_tpu.analysis.graph`): where the
graph answers *what does this name mean across modules*, this pass answers
*which thread runs this code and what locks does it hold* — the three
ingredients of every static race/deadlock rule:

1. **Thread-root discovery.** Every ``threading.Thread(target=...)`` /
   ``threading.Timer(..., fn)`` construction is a root; the target is
   resolved through the same alias/partial/pass-through chains the graph
   follows for callables, plus three shapes the graph alone can't see:
   ``self._method`` bound targets, nested-``def`` closures (the launch
   supervisor's ``_killer``, ``spawn_server_thread``'s ``run``), and
   methods reached through parameter type annotations
   (``def spawn_server_thread(server: PServer)``). Everything not
   reachable from a spawned root belongs to the synthetic ``main`` root.

2. **Shared-state inference.** ``self.`` attributes (identity: the class
   that owns them), module globals written through ``global``
   declarations, and closure variables of thread-spawning functions.
   An attribute/variable holding a synchronization primitive
   (``Lock``/``Event``/``Condition``/``Thread``/``make_lock``...) is the
   *protection*, not the protected — excluded from state tracking.

3. **Per-access locksets.** A DFS from each root walks ``with <lock>:``
   scopes (the MPT006 lock-name heuristic, with condition variables
   INCLUDED — ``with cond:`` acquires the condition's lock and protects
   state exactly like a lock; only the *blocking* rules exempt them) and
   carries the held set through the call graph — the generalisation of
   the one-level helper-wrapper taint :mod:`mpit_tpu.analysis.protocol`
   applies to sends. Along the way it records lock-order edges
   (held → acquiring, for MPT014 cycles) and blocking calls made while a
   lock acquired in an *ancestor* frame is held (MPT015 — the
   cross-function escalation of the intraprocedural MPT006).

Lock identity is static, not per-instance: ``self._dst_lock(dst)`` is one
lock node even though every destination gets its own instance — the sound
direction for lockset consistency (instances of one role protect one
role's state), and the same collapsing RT101 documents for names.

Like every analysis module: scanned code is parsed, NEVER imported.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

#: call-graph depth bound per root (also the recursion guard)
MAX_CALL_DEPTH = 12
#: virtual-dispatch fan-out bound when an annotated base class's method is
#: an abstract stub and the concrete overrides are walked instead
MAX_DISPATCH = 6

#: constructors whose result is a synchronization primitive (or a thread
#: handle): an attribute/variable initialized from one of these is the
#: protection mechanism itself, not shared data to protect
_SYNC_CONSTRUCTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Thread", "Timer", "local",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "make_lock", "make_condition",
}

#: sync constructors whose product is lock-like: entering it as a context
#: manager (or .acquire()) protects state
_LOCK_CONSTRUCTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "make_lock", "make_condition",
}

#: method names that mutate their receiver in place — a call on a tracked
#: state expression counts as a write to it
_MUTATORS = {
    "append", "appendleft", "add", "remove", "discard", "pop", "popleft",
    "clear", "update", "extend", "insert", "setdefault", "sort", "reverse",
}

#: indefinitely-blocking call names (rules/locks.py's MPT006 set, plus the
#: sleep/subprocess names only a call-graph-deep rule can afford to flag —
#: intraprocedurally they are too common under short critical sections)
_BLOCKING = {
    "sendall", "connect", "create_connection", "accept", "recv", "irecv",
    "send", "isend", "wait", "join",
    "sleep", "communicate", "check_call", "check_output",
}
#: names blocking only with a fully-qualified prefix ("run" alone would
#: flag every worker loop; subprocess.run is the blocking one)
_BLOCKING_DOTTED = {"subprocess.run", "subprocess.check_call",
                    "subprocess.check_output"}
_SEND_MIN_ARGS = {"send": 1, "isend": 1}

_THREAD_CTORS = {"Thread": (1, "target"), "Timer": (1, "function")}


def _lockish(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return "lock" in low or "mutex" in low or "cond" in low


# ---------------------------------------------------------------------------
# data model


@dataclasses.dataclass(frozen=True)
class StateKey:
    """Identity of one piece of tracked state (or one static lock).

    kind: ``attr`` (owner = defining class, dotted), ``global`` (owner =
    module) or ``local`` (owner = the closure-owning function)."""

    kind: str
    owner: str
    name: str

    def label(self) -> str:
        return f"{self.owner}.{self.name}"

    def short(self) -> str:
        return f"{self.owner.rsplit('.', 1)[-1]}.{self.name}"


@dataclasses.dataclass
class Access:
    state: StateKey
    write: bool
    root: str
    lockset: frozenset  # of StateKey lock ids
    init: bool  # __init__/pre-spawn setup phase — exempt from race pairing
    const_write: bool  # ``x = <literal>`` — the GIL-atomic stop-flag idiom
    mod: object  # ModuleCtx
    node: ast.AST


@dataclasses.dataclass
class LockEdge:
    held: StateKey
    acquired: StateKey
    root: str
    mod: object
    node: ast.AST
    symbol: str


@dataclasses.dataclass
class BlockingSite:
    call: str
    lockset: frozenset  # effective held set (receiver cond excluded)
    cross_locks: frozenset  # held locks acquired in an ANCESTOR frame
    root: str
    mod: object
    node: ast.AST


@dataclasses.dataclass
class ThreadRoot:
    name: str  # thread name= literal when present, else target qualname
    target_desc: str
    mod: object  # ModuleCtx of the spawn site
    node: ast.AST  # the Thread(...) call
    resolved: bool


@dataclasses.dataclass
class _ClassInfo:
    key: str  # absolute dotted "pkg.mod.Class"
    name: str
    mod: object  # ModuleCtx
    node: ast.ClassDef
    methods: dict  # name -> FunctionDef
    bases: list = dataclasses.field(default_factory=list)  # resolved keys
    attr_types: dict = dataclasses.field(default_factory=dict)
    sync_attrs: set = dataclasses.field(default_factory=set)
    # the subset of sync_attrs that are lock-LIKE (usable as ``with x:``
    # protection): self._cv = threading.Condition() guards state even
    # though nothing in the attr name says so
    lock_attrs: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _ClosureInfo:
    owner: str  # dotted qualname of the spawning function
    names: set  # names shared between the owner's scope and nested defs
    spawn_line: int  # first Thread() construction in the owner's own body
    sync_names: set  # closure names bound to sync constructors


class ThreadModel:
    """The whole-program concurrency map the MPT013–015 rules consume."""

    def __init__(self, roots, accesses, edges, blocking):
        self.roots: list = roots
        self.accesses: list = accesses
        self.edges: list = edges
        self.blocking: list = blocking

    # -- aggregation ------------------------------------------------------

    def state_map(self) -> dict:
        """state -> root -> {reads, writes, locksets, example accesses}."""
        out: dict = {}
        for a in self.accesses:
            if a.init:
                continue
            per_root = out.setdefault(a.state, {})
            entry = per_root.setdefault(
                a.root,
                {"reads": 0, "writes": 0, "locksets": set(),
                 "write_locksets": set(), "example": a,
                 "write_example": None, "all_const_writes": True},
            )
            entry["reads" if not a.write else "writes"] += 1
            entry["locksets"].add(a.lockset)
            if a.write:
                entry["write_locksets"].add(a.lockset)
                if not a.const_write:
                    entry["all_const_writes"] = False
                if entry["write_example"] is None or (
                    not a.lockset and entry["write_example"].lockset
                ):
                    entry["write_example"] = a
        return out

    def shared_state(self, min_roots: int = 2) -> dict:
        return {
            state: per_root
            for state, per_root in self.state_map().items()
            if len(per_root) >= min_roots
        }

    def owner_state(self, owner_suffix: str) -> dict:
        """Every tracked state of one owner (class/module), shared or not
        — the threading-model doc's per-subsystem enumeration."""
        return {
            state: per_root
            for state, per_root in self.state_map().items()
            if state.owner.endswith(owner_suffix)
        }

    def lock_cycles(self) -> list:
        """Simple cycles in the static lock-order graph, deduplicated by
        node set; each as (cycle_nodes, example_edges)."""
        graph: dict = {}
        edge_by_pair: dict = {}
        for e in self.edges:
            if e.held == e.acquired:
                continue  # reentrant/per-instance aliasing, not an order
            graph.setdefault(e.held, set()).add(e.acquired)
            edge_by_pair.setdefault((e.held, e.acquired), e)
        cycles: list = []
        seen_sets: set = set()
        for start in graph:
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key not in seen_sets:
                            seen_sets.add(key)
                            pairs = list(zip(path, path[1:] + [start]))
                            cycles.append(
                                (path, [edge_by_pair[p] for p in pairs])
                            )
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))
        return cycles

    # -- serialization ----------------------------------------------------

    def to_json(self) -> dict:
        roots = [
            {
                "name": r.name,
                "target": r.target_desc,
                "spawned_at": f"{r.mod.rel}:{r.node.lineno}",
                "resolved": r.resolved,
            }
            for r in sorted(self.roots, key=lambda r: r.name)
        ]
        shared = []
        for state, per_root in sorted(
            self.shared_state().items(), key=lambda kv: kv[0].label()
        ):
            shared.append({
                "state": state.label(),
                "kind": state.kind,
                "roots": {
                    root: {
                        "reads": e["reads"],
                        "writes": e["writes"],
                        "locksets": sorted(
                            sorted(l.short() for l in ls)
                            for ls in e["locksets"]
                        ),
                    }
                    for root, e in sorted(per_root.items())
                },
            })
        return {
            "roots": roots,
            "shared_state": shared,
            "lock_edges": sorted({
                f"{e.held.short()} -> {e.acquired.short()}"
                for e in self.edges if e.held != e.acquired
            }),
        }


# ---------------------------------------------------------------------------
# scopes


@dataclasses.dataclass
class _Scope:
    fn: ast.AST  # FunctionDef
    mod: object  # ModuleCtx
    info: object  # ModuleInfo (graph)
    self_class: Optional[str]
    types: dict  # local name -> class key
    aliases: dict  # local name -> simple assigned expr (lock aliasing)
    globals_: set  # names declared ``global`` in this function
    assigned: set  # names stored anywhere in this function's own scope
    nonlocals: set
    closure: Optional[_ClosureInfo]
    closure_is_owner: bool  # walking the spawning function itself?
    nested: dict  # name -> nested FunctionDef


@dataclasses.dataclass
class _Frame:
    """One call-graph frame's walk state (lockset is carried, not copied
    per statement: With scopes push/pop)."""

    scope: _Scope
    root: str
    init: bool
    depth: int  # call-graph depth (frame index)


class _Analyzer:
    def __init__(self, project):
        self.project = project
        self.graph = project.graph
        self.modules = list(project.modules)
        self.classes: dict = {}  # key -> _ClassInfo
        self.class_local: dict = {}  # mod.rel -> {local name: key}
        self.subclasses: dict = {}  # key -> [subclass keys]
        self.global_written: dict = {}  # mod.rel -> set of global names
        self.roots: list = []
        self.accesses: list = []
        self.edges: list = []
        self.blocking: list = []
        self._root_entries: list = []  # (root_name, callee-tuple)
        self._closures: dict = {}  # id(owner fn) -> _ClosureInfo
        self._root_reached: set = set()  # id(fn) reached from spawned roots
        self._memo: set = set()
        self._fn_prescan: dict = {}  # id(fn) -> (assigned, globals, nonlocals, nested)
        self._init_only: set = set()  # id(fn) reachable ONLY from __init__

    # -- indexing ---------------------------------------------------------

    def _info(self, mod):
        return self.graph.module_for_rel(mod.rel)

    def build_index(self) -> None:
        for mod in self.modules:
            info = self._info(mod)
            if info is None:
                continue
            local: dict = {}
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    methods = {
                        n.name: n
                        for n in node.body
                        if isinstance(
                            n, (ast.FunctionDef, ast.AsyncFunctionDef)
                        )
                    }
                    key = f"{info.name}.{node.name}"
                    self.classes[key] = _ClassInfo(
                        key=key, name=node.name, mod=mod, node=node,
                        methods=methods,
                    )
                    local[node.name] = key
            self.class_local[mod.rel] = local
            written = set()
            for node in mod.nodes:
                if isinstance(node, ast.Global):
                    written.update(node.names)
            self.global_written[mod.rel] = written
        self._compute_init_only()
        # second pass: bases and attribute types need the full class table
        for ci in self.classes.values():
            info = self._info(ci.mod)
            for base in ci.node.bases:
                dotted = astutil.dotted_name(base)
                key = self._resolve_class(info, dotted) if dotted else None
                if key is not None:
                    ci.bases.append(key)
                    self.subclasses.setdefault(key, []).append(ci.key)
            self._scan_attr_types(ci, info)

    def _compute_init_only(self) -> None:
        """Functions whose every (name-matched) call site sits inside
        construction code are init-phase: ``PServer._restore_shard`` and
        the ``load_state`` helpers run strictly before the server thread
        exists. Name-matched = conservative: a same-named method called
        anywhere in steady state keeps the whole name steady-state."""
        call_sites: dict = {}  # callee last-name -> [caller fn id or None]
        all_fns: dict = {}  # id -> fn
        for mod in self.modules:
            for node in mod.nodes:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    all_fns[id(node)] = node
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.call_last_name(node)
                if not name:
                    continue
                cur = mod.parents.get(node)
                while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    cur = mod.parents.get(cur)
                call_sites.setdefault(name, []).append(
                    id(cur) if cur is not None else None
                )
        init_ids = {
            fid for fid, fn in all_fns.items()
            if fn.name in ("__init__", "__post_init__")
        }
        changed = True
        while changed:
            changed = False
            for fid, fn in all_fns.items():
                if fid in init_ids:
                    continue
                callers = call_sites.get(fn.name)
                if callers and all(
                    c is not None and c in init_ids for c in callers
                ):
                    init_ids.add(fid)
                    changed = True
        self._init_only = init_ids

    def _scan_attr_types(self, ci: _ClassInfo, info) -> None:
        for mname, fn in ci.methods.items():
            ann_types = self._param_types(fn, info)
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                ):
                    continue
                attr = node.targets[0].attr
                val = node.value
                # ``x if cond else Ctor()``: either arm types the attr
                vals = (
                    [val.body, val.orelse] if isinstance(val, ast.IfExp)
                    else [val]
                )
                for v in vals:
                    if isinstance(v, ast.Call):
                        last = astutil.call_last_name(v)
                        if last in _SYNC_CONSTRUCTORS:
                            ci.sync_attrs.add(attr)
                            if last in _LOCK_CONSTRUCTORS:
                                ci.lock_attrs.add(attr)
                            break
                        dotted = astutil.dotted_name(v.func)
                        key = (
                            self._resolve_class(info, dotted)
                            if dotted else None
                        )
                        if key is not None:
                            ci.attr_types.setdefault(attr, key)
                    elif isinstance(v, ast.Name) and v.id in ann_types:
                        ci.attr_types.setdefault(attr, ann_types[v.id])

    def _param_types(self, fn, info) -> dict:
        out: dict = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) + list(
            fn.args.kwonlyargs
        )
        for a in args:
            if a.annotation is None:
                continue
            ann = a.annotation
            dotted = None
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                dotted = ann.value  # forward reference
            else:
                dotted = astutil.dotted_name(ann)
            if dotted:
                key = self._resolve_class(info, dotted)
                if key is not None:
                    out[a.arg] = key
        return out

    def _resolve_class(self, info, dotted: Optional[str]) -> Optional[str]:
        if info is None or not dotted:
            return None
        parts = dotted.split(".")
        local = self.class_local.get(info.rel, {})
        if len(parts) == 1 and parts[0] in local:
            return local[parts[0]]
        head = parts[0]
        if head in info.imports:
            target = info.imports[head]
            rest = ".".join(parts[1:])
            return self._resolve_class_abs(
                f"{target}.{rest}" if rest else target
            )
        if len(parts) > 1:
            return self._resolve_class_abs(dotted)
        return None

    def _resolve_class_abs(
        self, dotted: str, depth: int = 0
    ) -> Optional[str]:
        if depth > 8:
            return None
        if dotted in self.classes:
            return dotted
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:cut])
            mod = self.graph.by_name.get(modname)
            if mod is None:
                continue
            rest = parts[cut:]
            if len(rest) != 1:
                return None
            name = rest[0]
            key = f"{modname}.{name}"
            if key in self.classes:
                return key
            if name in mod.imports:  # package __init__ re-export
                return self._resolve_class_abs(mod.imports[name], depth + 1)
            return None
        return None

    def _find_method(self, key: str, name: str, depth: int = 0):
        """(defining-ish class key, FunctionDef) through the base chain."""
        if depth > 6:
            return None
        ci = self.classes.get(key)
        if ci is None:
            return None
        if name in ci.methods:
            return key, ci.methods[name]
        for base in ci.bases:
            hit = self._find_method(base, name, depth + 1)
            if hit is not None:
                return hit
        return None

    def _all_subclasses(self, key: str) -> list:
        out, frontier = [], list(self.subclasses.get(key, ()))
        while frontier and len(out) < MAX_DISPATCH:
            k = frontier.pop()
            if k in out:
                continue
            out.append(k)
            frontier.extend(self.subclasses.get(k, ()))
        return out

    @staticmethod
    def _is_stub(fn) -> bool:
        body = fn.body
        if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant
        ):
            body = body[1:]  # docstring
        return all(
            isinstance(s, (ast.Raise, ast.Pass))
            or (
                isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Constant)
                and s.value.value is Ellipsis
            )
            for s in body
        ) if body else True

    def _dispatch(self, key: str, mname: str) -> list:
        """Concrete (class key, fn) targets for ``obj.m()`` where obj has
        static class ``key`` — subclass overrides when the statically
        found method is an abstract stub (the Transport pattern)."""
        hit = self._find_method(key, mname)
        if hit is not None and not self._is_stub(hit[1]):
            return [(key, hit[1])]
        out = []
        for sub in self._all_subclasses(key):
            sci = self.classes.get(sub)
            if sci and mname in sci.methods and not self._is_stub(
                sci.methods[mname]
            ):
                out.append((sub, sci.methods[mname]))
        if not out and hit is not None:
            out.append((key, hit[1]))
        return out[:MAX_DISPATCH]

    # -- function prescan --------------------------------------------------

    def _prescan(self, fn):
        cached = self._fn_prescan.get(id(fn))
        if cached is not None:
            return cached
        assigned: set = set()
        globals_: set = set()
        nonlocals: set = set()
        nested: dict = {}
        aliases: dict = {}

        def scan(body):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested[node.name] = node
                    assigned.add(node.name)
                    continue
                if isinstance(node, ast.ClassDef):
                    assigned.add(node.name)
                    continue
                if isinstance(node, ast.Global):
                    globals_.update(node.names)
                elif isinstance(node, ast.Nonlocal):
                    nonlocals.update(node.names)
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)):
                        # compound statements re-enter via scan(); walk
                        # from a stmt can still reach a def nested in an
                        # if/for body — record, don't descend further
                        nested.setdefault(sub.name, sub)
                        assigned.add(sub.name)
                    elif isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, (ast.Store, ast.Del)
                    ):
                        assigned.add(sub.id)
                    elif isinstance(sub, ast.Assign) and len(
                        sub.targets
                    ) == 1 and isinstance(sub.targets[0], ast.Name):
                        aliases.setdefault(sub.targets[0].id, sub.value)

        scan(fn.body)
        for a in (
            list(fn.args.posonlyargs) + list(fn.args.args)
            + list(fn.args.kwonlyargs)
            + ([fn.args.vararg] if fn.args.vararg else [])
            + ([fn.args.kwarg] if fn.args.kwarg else [])
        ):
            assigned.add(a.arg)
        out = (assigned, globals_, nonlocals, nested, aliases)
        self._fn_prescan[id(fn)] = out
        return out

    def _make_scope(
        self, fn, mod, self_class, closure, closure_is_owner,
        inherited_types=None,
    ) -> _Scope:
        info = self._info(mod)
        assigned, globals_, nonlocals, nested, aliases = self._prescan(fn)
        types = dict(inherited_types or {})
        types.update(self._param_types(fn, info))
        if self_class is not None:
            types["self"] = self_class
        # local constructor calls type locals: ``broker = Broker(n)``
        for name, expr in aliases.items():
            if isinstance(expr, ast.Call):
                dotted = astutil.dotted_name(expr.func)
                key = self._resolve_class(info, dotted) if dotted else None
                if key is not None:
                    types.setdefault(name, key)
        return _Scope(
            fn=fn, mod=mod, info=info, self_class=self_class,
            types=types, aliases=aliases, globals_=globals_,
            assigned=assigned, nonlocals=nonlocals, closure=closure,
            closure_is_owner=closure_is_owner, nested=nested,
        )

    # -- thread-root discovery ---------------------------------------------

    def discover_roots(self) -> None:
        for mod in self.modules:
            info = self._info(mod)
            for node in mod.nodes:
                if not isinstance(node, ast.Call):
                    continue
                last = astutil.call_last_name(node)
                if last not in _THREAD_CTORS:
                    continue
                dotted = astutil.dotted_name(node.func)
                if dotted is not None and "." in dotted and not (
                    dotted.startswith("threading.")
                ):
                    continue  # some other Thread-named constructor
                pos, kw = _THREAD_CTORS[last]
                target = astutil.get_arg(node, pos, kw)
                if target is None:
                    continue
                self._register_root(mod, info, node, target)

    def _thread_name(self, node: ast.Call) -> Optional[str]:
        arg = astutil.get_arg(node, 2, "name")
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        return None

    def _enclosing_fn_chain(self, mod, node) -> list:
        """Innermost-first FunctionDefs (and the enclosing ClassDef, last)
        containing ``node``."""
        chain = []
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                chain.append(cur)
            cur = mod.parents.get(cur)
        return chain

    def _register_root(self, mod, info, node, target) -> None:
        chain = self._enclosing_fn_chain(mod, node)
        fns = [c for c in chain if isinstance(
            c, (ast.FunctionDef, ast.AsyncFunctionDef))]
        cls = next((c for c in chain if isinstance(c, ast.ClassDef)), None)
        cls_key = (
            self.class_local.get(mod.rel, {}).get(cls.name) if cls else None
        )
        name = self._thread_name(node)
        desc = astutil.dotted_name(target) or "<expr>"
        entry = None  # (fn, mod, self_class, closure, inherited_types)

        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
        ):
            base = target.value.id
            recv_key = None
            if base == "self" and cls_key is not None:
                recv_key = cls_key
            else:
                # a typed local/param: spawn_server_thread-style
                for fn in fns:
                    sc_types = self._param_types(fn, info)
                    if base in sc_types:
                        recv_key = sc_types[base]
                        break
            if recv_key is not None:
                for tkey, tfn in self._dispatch(recv_key, target.attr):
                    entry = (tfn, self.classes[tkey].mod, tkey, None, None)
                    break
                desc = f"{recv_key.rsplit('.', 1)[-1]}.{target.attr}"
        elif isinstance(target, ast.Name):
            # nearest enclosing function defining it as a nested def
            for depth_i, fn in enumerate(fns):
                _, _, _, nested, _ = self._prescan(fn)
                if target.id in nested:
                    closure = self._closure_for(fn, mod, fns[depth_i + 1:])
                    inherited = self._make_scope(
                        fn, mod,
                        cls_key if fn is fns[-1] and cls else None,
                        None, False,
                    ).types
                    entry = (nested[target.id], mod, None, closure,
                             inherited)
                    desc = f"{fn.name}.<{target.id}>"
                    break
            if entry is None:
                ci = self.graph.resolve_callable(info, target)
                if ci is not None:
                    cmod = self._ctx_for_info(ci.module)
                    if cmod is not None:
                        entry = (ci.fn, cmod, None, None, None)
                        desc = f"{ci.module.name}.{ci.fn.name}"
        else:
            ci = self.graph.resolve_callable(info, target)
            if ci is not None:
                cmod = self._ctx_for_info(ci.module)
                if cmod is not None:
                    entry = (ci.fn, cmod, None, None, None)
                    desc = f"{ci.module.name}.{ci.fn.name}"

        root_name = name or desc
        self.roots.append(ThreadRoot(
            name=root_name, target_desc=desc, mod=mod, node=node,
            resolved=entry is not None,
        ))
        if entry is not None:
            self._root_entries.append((root_name, entry))

    def _ctx_for_info(self, info):
        for m in self.modules:
            if m.rel == info.rel:
                return m
        return None

    def _closure_for(self, owner_fn, mod, outer_fns) -> _ClosureInfo:
        ci = self._closures.get(id(owner_fn))
        if ci is not None:
            return ci
        info = self._info(mod)
        owner_assigned, _, _, nested, aliases = self._prescan(owner_fn)
        referenced: set = set()
        for nfn in nested.values():
            n_assigned, _, n_nonlocals, _, _ = self._prescan(nfn)
            for sub in ast.walk(nfn):
                if isinstance(sub, ast.Name):
                    if sub.id in n_assigned and sub.id not in n_nonlocals:
                        continue  # the nested def's own local
                    referenced.add(sub.id)
        shared = owner_assigned & referenced
        sync_names = {
            n for n in shared
            if isinstance(aliases.get(n), ast.Call)
            and astutil.call_last_name(aliases[n]) in _SYNC_CONSTRUCTORS
        }
        # first Thread construction in the owner's own body (nested defs
        # excluded): assignments before it are pre-spawn setup — the
        # closure equivalent of the __init__ exemption
        spawn_line = 10 ** 9
        for node in ast.walk(owner_fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not owner_fn:
                    continue
            if isinstance(node, ast.Call) and astutil.call_last_name(
                node
            ) in _THREAD_CTORS:
                chain = []
                # cheap containment check: is this call inside a nested def?
                pass
        spawn_line = self._first_spawn_line(owner_fn, nested)
        qual = f"{info.name}.{owner_fn.name}" if info else owner_fn.name
        ci = _ClosureInfo(
            owner=qual, names=shared - sync_names, spawn_line=spawn_line,
            sync_names=sync_names,
        )
        self._closures[id(owner_fn)] = ci
        return ci

    @staticmethod
    def _first_spawn_line(owner_fn, nested) -> int:
        nested_ids = {id(n) for n in nested.values()}
        first = 10 ** 9

        def walk(node):
            nonlocal first
            for child in ast.iter_child_nodes(node):
                if id(child) in nested_ids:
                    continue
                if isinstance(child, ast.Call) and astutil.call_last_name(
                    child
                ) in _THREAD_CTORS:
                    first = min(first, child.lineno)
                walk(child)

        walk(owner_fn)
        return first

    # -- traversal ---------------------------------------------------------

    def run(self) -> ThreadModel:
        self.build_index()
        self.discover_roots()
        for root_name, entry in self._root_entries:
            self._walk_entry(root_name, entry, init=False,
                             record_reach=True)
        # everything not reachable from a spawned root runs on the main
        # thread (or a thread this pass cannot see — same conservative
        # bucket); __init__ bodies are construction, not steady state
        for mod in self.modules:
            info = self._info(mod)
            if info is None:
                continue
            for fn in info.functions.values():
                if id(fn) in self._root_reached:
                    continue
                closure = self._closures.get(id(fn))
                self._walk_entry(
                    "main", (fn, mod, None, closure, None),
                    init=id(fn) in self._init_only, record_reach=False,
                    closure_is_owner=closure is not None,
                )
            for cls_key in self.class_local.get(mod.rel, {}).values():
                ci = self.classes[cls_key]
                for mname, mfn in ci.methods.items():
                    if id(mfn) in self._root_reached:
                        continue
                    closure = self._closures.get(id(mfn))
                    self._walk_entry(
                        "main", (mfn, mod, cls_key, closure, None),
                        init=(
                            mname in ("__init__", "__post_init__")
                            or id(mfn) in self._init_only
                        ),
                        record_reach=False,
                        closure_is_owner=closure is not None,
                    )
        return ThreadModel(
            self.roots, self.accesses, self.edges, self.blocking
        )

    def _walk_entry(
        self, root, entry, init, record_reach, closure_is_owner=False
    ) -> None:
        fn, mod, self_class, closure, inherited = entry
        self._walk_fn(
            fn, mod, self_class, closure, closure_is_owner, inherited,
            root=root, lockset={}, init=init, depth=0,
            record_reach=record_reach,
        )

    def _walk_fn(
        self, fn, mod, self_class, closure, closure_is_owner, inherited,
        root, lockset, init, depth, record_reach,
    ) -> None:
        if depth > MAX_CALL_DEPTH:
            return
        key = (id(fn), self_class, root, frozenset(lockset), init)
        if key in self._memo:
            return
        self._memo.add(key)
        if record_reach:
            self._root_reached.add(id(fn))
        scope = self._make_scope(
            fn, mod, self_class, closure, closure_is_owner, inherited
        )
        frame = _Frame(scope=scope, root=root, init=init, depth=depth)
        self._walk_body(
            fn.body, frame, dict(lockset), record_reach
        )

    # lockset is a dict lock-id -> frame-depth-at-acquisition

    def _walk_body(self, body, frame, lockset, record_reach) -> None:
        for stmt in body:
            self._walk_stmt(stmt, frame, lockset, record_reach)

    def _walk_stmt(self, stmt, frame, lockset, record_reach) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # analyzed when called / as a thread target
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                self._scan_expr(item.context_expr, frame, lockset,
                                record_reach)
                lid = self._lock_id(item.context_expr, frame.scope)
                if lid is not None and lid not in lockset:
                    for held in lockset:
                        self._record_edge(held, lid, frame, stmt)
                    lockset[lid] = frame.depth
                    acquired.append(lid)
            self._walk_body(stmt.body, frame, lockset, record_reach)
            for lid in acquired:
                del lockset[lid]
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            for expr in ast.iter_child_nodes(stmt):
                if not isinstance(expr, ast.stmt):
                    self._scan_expr(expr, frame, lockset, record_reach)
            for sub in getattr(stmt, "body", ()):
                self._walk_stmt(sub, frame, lockset, record_reach)
            for sub in getattr(stmt, "orelse", ()):
                self._walk_stmt(sub, frame, lockset, record_reach)
            return
        if isinstance(stmt, ast.Try):
            for part in (stmt.body, stmt.orelse, stmt.finalbody):
                self._walk_body(part, frame, lockset, record_reach)
            for h in stmt.handlers:
                self._walk_body(h.body, frame, lockset, record_reach)
            return
        if isinstance(stmt, ast.Assign):
            const = isinstance(stmt.value, ast.Constant)
            for tgt in stmt.targets:
                self._record_store(tgt, frame, lockset, const)
            self._scan_expr(stmt.value, frame, lockset, record_reach)
            return
        if isinstance(stmt, ast.AugAssign):
            self._record_store(stmt.target, frame, lockset, False)
            self._scan_expr(stmt.value, frame, lockset, record_reach)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._record_store(
                    stmt.target, frame, lockset,
                    isinstance(stmt.value, ast.Constant),
                )
                self._scan_expr(stmt.value, frame, lockset, record_reach)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._record_store(tgt, frame, lockset, False)
            return
        # Return/Expr/Raise/Assert/...: scan contained expressions
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._walk_stmt(child, frame, lockset, record_reach)
            else:
                self._scan_expr(child, frame, lockset, record_reach)

    # -- expression scan ---------------------------------------------------

    def _scan_expr(self, expr, frame, lockset, record_reach) -> None:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                self._handle_call(node, frame, lockset, record_reach)
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Attribute):
                state = self._state_of(node, frame.scope)
                if state is not None:
                    self._record(
                        state,
                        isinstance(node.ctx, (ast.Store, ast.Del)),
                        frame, lockset, node, const=False,
                    )
                stack.append(node.value)
                continue
            if isinstance(node, ast.Name):
                state = self._state_of(node, frame.scope)
                if state is not None:
                    self._record(
                        state,
                        isinstance(node.ctx, (ast.Store, ast.Del)),
                        frame, lockset, node, const=False,
                    )
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _record_store(self, target, frame, lockset, const) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_store(el, frame, lockset, const)
            return
        if isinstance(target, ast.Starred):
            self._record_store(target.value, frame, lockset, const)
            return
        if isinstance(target, ast.Subscript):
            # a[k] = v mutates a: the container write the lockset rules
            # care about (const exemption never applies to item stores)
            base = target.value
            while isinstance(base, ast.Subscript):
                base = base.value
            state = self._state_of(base, frame.scope)
            if state is not None:
                self._record(state, True, frame, lockset, target, False)
            self._scan_expr(target.slice, frame, lockset, False)
            return
        state = self._state_of(target, frame.scope)
        if state is not None:
            self._record(state, True, frame, lockset, target, const)
        elif isinstance(target, ast.Attribute):
            self._scan_expr(target.value, frame, lockset, False)

    def _record(self, state, write, frame, lockset, node, const) -> None:
        self.accesses.append(Access(
            state=state, write=write, root=frame.root,
            lockset=frozenset(lockset),
            init=frame.init or self._is_presetup(frame, node),
            const_write=const and write,
            mod=frame.scope.mod, node=node,
        ))

    @staticmethod
    def _is_presetup(frame, node) -> bool:
        """Closure-owner writes before the first thread spawn are setup."""
        sc = frame.scope
        return (
            sc.closure is not None
            and sc.closure_is_owner
            and getattr(node, "lineno", 0) < sc.closure.spawn_line
        )

    def _record_edge(self, held, acquired, frame, node) -> None:
        if held == acquired:
            return
        self.edges.append(LockEdge(
            held=held, acquired=acquired, root=frame.root,
            mod=frame.scope.mod, node=node,
            symbol=astutil.enclosing_symbol(node, frame.scope.mod.parents),
        ))

    # -- state / lock identity ---------------------------------------------

    def _receiver_class(self, expr, scope) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return scope.types.get(expr.id)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
        ):
            base_cls = scope.types.get(expr.value.id)
            if base_cls is not None:
                ci = self.classes.get(base_cls)
                if ci is not None:
                    return ci.attr_types.get(expr.attr)
        return None

    def _state_of(self, expr, scope) -> Optional[StateKey]:
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Subscript):
                recv = recv.value
            cls = self._receiver_class(recv, scope)
            if cls is None:
                return None
            ci = self.classes.get(cls)
            if ci is None:
                return None
            attr = expr.attr
            if (
                _lockish(attr)
                or attr in ci.sync_attrs
                or attr in ci.methods
            ):
                return None
            return StateKey("attr", cls, attr)
        if isinstance(expr, ast.Name):
            name = expr.id
            if _lockish(name):
                return None
            sc = scope
            if sc.closure is not None and name in sc.closure.names:
                if sc.closure_is_owner or (
                    name not in sc.assigned or name in sc.nonlocals
                ):
                    return StateKey("local", sc.closure.owner, name)
            if name in sc.globals_ or (
                isinstance(expr.ctx, ast.Load)
                and sc.info is not None
                and name in self.global_written.get(sc.mod.rel, ())
            ):
                if sc.info is not None:
                    return StateKey("global", sc.info.name, name)
            return None
        return None

    def _lock_id(
        self, expr, scope, depth: int = 0
    ) -> Optional[StateKey]:
        if depth > 4:
            return None
        cur = expr
        if isinstance(cur, ast.Call):
            cur = cur.func  # self._dst_lock(dst)
        if isinstance(cur, ast.Subscript):
            cur = cur.value  # self._conds[i]
        if isinstance(cur, ast.Attribute):
            recv = cur.value
            if isinstance(recv, ast.Subscript):
                recv = recv.value
            cls = self._receiver_class(recv, scope)
            if cls is not None:
                ci = self.classes.get(cls)
                if _lockish(cur.attr) or (
                    ci is not None and cur.attr in ci.lock_attrs
                ):
                    return StateKey("attr", cls, cur.attr)
            if not _lockish(cur.attr):
                return None
            dotted = astutil.dotted_name(cur)
            if dotted is not None and scope.info is not None:
                # module-attribute lock: mod._lock
                r = self.graph.resolve(scope.info, dotted)
                if r is not None and r.module is not None:
                    return StateKey("global", r.module.name, cur.attr)
            if isinstance(cur.value, ast.Name) and cur.value.id == "self":
                owner = scope.self_class or (
                    scope.info.name if scope.info else scope.mod.rel
                )
                return StateKey("attr", owner, cur.attr)
            return None
        if isinstance(cur, ast.Name):
            name = cur.id
            aliased = scope.aliases.get(name)
            if (
                aliased is not None
                and not isinstance(aliased, ast.Name)
                # a constructor call IS the lock: the local name is its
                # identity — following the alias would collapse every
                # ``x = make_lock(...)`` local onto the factory's name
                and not (
                    isinstance(aliased, ast.Call)
                    and astutil.call_last_name(aliased)
                    in _LOCK_CONSTRUCTORS
                )
            ):
                via = self._lock_id(aliased, scope, depth + 1)
                if via is not None:
                    return via
            if not _lockish(name):
                return None
            sc = scope
            if sc.closure is not None and (
                name in sc.closure.names or name in sc.closure.sync_names
            ):
                return StateKey("local", sc.closure.owner, name)
            if sc.info is not None and (
                name in sc.globals_
                or name in sc.info.assigns
                or name in sc.info.constants
            ):
                return StateKey("global", sc.info.name, name)
            owner = f"{sc.info.name}.{sc.fn.name}" if sc.info else sc.fn.name
            return StateKey("local", owner, name)
        return None

    # -- calls -------------------------------------------------------------

    def _handle_call(self, call, frame, lockset, record_reach) -> None:
        name = astutil.call_last_name(call)
        scope = frame.scope
        # explicit .acquire(): an order edge even without a with-scope
        if name == "acquire" and isinstance(call.func, ast.Attribute):
            lid = self._lock_id(call.func.value, scope)
            if lid is not None:
                for held in lockset:
                    self._record_edge(held, lid, frame, call)
        # blocking call while holding a lock acquired in an ancestor frame
        if lockset and (
            name in _BLOCKING
            or (astutil.dotted_name(call.func) in _BLOCKING_DOTTED)
        ):
            self._check_blocking(call, name, frame, lockset)
        # mutating method on tracked state — unless the receiver's class
        # defines the method itself (FaultLog.append locks internally;
        # _descend walks the real body instead of guessing)
        if (
            name in _MUTATORS
            and isinstance(call.func, ast.Attribute)
        ):
            recv = call.func.value
            recv_cls = self._receiver_class(
                recv.value if isinstance(recv, ast.Subscript) else recv,
                scope,
            )
            if recv_cls is None or self._find_method(
                recv_cls, name
            ) is None:
                state = self._state_of(recv, scope)
                if state is None and isinstance(recv, ast.Subscript):
                    state = self._state_of(recv.value, scope)
                if state is not None:
                    self._record(state, True, frame, lockset, call, False)
        # descend into resolvable callees
        self._descend(call, frame, lockset, record_reach)

    def _check_blocking(self, call, name, frame, lockset) -> None:
        if name in _SEND_MIN_ARGS and (
            len(call.args) + len(call.keywords) < _SEND_MIN_ARGS[name]
        ):
            return
        if name == "join" and len(call.args) == 1:
            return  # "sep".join(parts)
        effective = dict(lockset)
        if name == "wait" and isinstance(call.func, ast.Attribute):
            # cond.wait() releases cond itself; only OTHER held locks are
            # held across the sleep
            recv = self._lock_id(call.func.value, frame.scope)
            if recv is not None:
                effective.pop(recv, None)
        if not effective:
            return
        cross = frozenset(
            l for l, d in effective.items() if d < frame.depth
        )
        if not cross:
            return  # same-frame: MPT006's intraprocedural jurisdiction
        self.blocking.append(BlockingSite(
            call=name, lockset=frozenset(effective), cross_locks=cross,
            root=frame.root, mod=frame.scope.mod, node=call,
        ))

    def _descend(self, call, frame, lockset, record_reach) -> None:
        scope = frame.scope
        func = call.func
        targets = []  # (fn, mod, self_class, closure, inherited_types)
        if isinstance(func, ast.Attribute):
            recv = func.value
            if isinstance(recv, ast.Subscript):
                recv = recv.value
            cls = self._receiver_class(recv, scope)
            if cls is not None:
                for tkey, tfn in self._dispatch(cls, func.attr):
                    targets.append(
                        (tfn, self.classes[tkey].mod, tkey, None, None)
                    )
            elif isinstance(recv, ast.Name) or isinstance(
                func.value, ast.Name
            ):
                ci = self.graph.resolve_callable(scope.info, func)
                if ci is not None:
                    cmod = self._ctx_for_info(ci.module)
                    if cmod is not None:
                        targets.append((ci.fn, cmod, None, None, None))
        elif isinstance(func, ast.Name):
            if func.id in scope.nested:
                # sibling/nested def: same closure family
                targets.append((
                    scope.nested[func.id], scope.mod, scope.self_class,
                    scope.closure
                    or self._closures.get(id(scope.fn)),
                    scope.types,
                ))
            else:
                local_cls = self.class_local.get(scope.mod.rel, {})
                if func.id in local_cls or self._resolve_class(
                    scope.info, func.id
                ):
                    targets = []  # constructor: __init__ walked as init
                else:
                    ci = self.graph.resolve_callable(scope.info, func)
                    if ci is not None:
                        cmod = self._ctx_for_info(ci.module)
                        if cmod is not None:
                            targets.append((ci.fn, cmod, None, None, None))
        for fn, mod, self_class, closure, inherited in targets[
            :MAX_DISPATCH
        ]:
            closure_is_owner = False
            if closure is not None and fn is not scope.fn:
                closure_is_owner = False
            self._walk_fn(
                fn, mod, self_class, closure, closure_is_owner, inherited,
                root=frame.root, lockset=lockset, init=frame.init,
                depth=frame.depth + 1, record_reach=record_reach,
            )


def build_model(project) -> ThreadModel:
    """Entry point: rules reach this through ``project.threads``."""
    return _Analyzer(project).run()
