"""Protocol-role model: per-role send/recv tag sequences, statically.

The host-async PS protocol is a conversation between two roles — the
pserver's wildcard-recv dispatch loop and the pclient's send/recv call
pattern — and its hardest failure class is cross-rank: a tag one role sends
that the counterpart never receives (the message parks forever and teardown
hangs), or both roles blocking in recv for a tag only the *other* side's
later send would satisfy. Rank-local lint rules cannot see either; this
module extracts the static halves from the AST so MPT008 can.

A module opts into a role with a marker comment anywhere at the top level::

    # mpit-analysis: protocol-role[client->server]

meaning "this module implements role ``client``, whose counterpart role is
``server``". Several modules may share one role (``pclient.py`` and
``ps_roles.py`` are both ``client``); their operations merge. The markers
live with the code — ``parallel/pserver.py``, ``parallel/pclient.py`` and
``parallel/ps_roles.py`` carry them — so the model needs no path
configuration and fixture packages participate the same way.

Extracted per role, with tags resolved to integers through the module graph
(``TAG_PARAM`` imported from ``pserver`` resolves to 4; unresolvable tag
expressions are skipped — conservative, no finding):

- **sends**: ``send``/``isend`` call sites (3+ args: the transport shape),
  including module-local indirection to a fixpoint — a function that
  forwards a tag parameter toward a transport send, directly
  (``PClient._send_with_retry``) or through another wrapper
  (``PClient._scatter`` riding the retry helper), counts its call sites
  (``self._scatter(TAG_PUSH_EASGD, ...)``) as sends of the resolved tag;
- **recvs**: ``recv``/``irecv``/``probe`` sites; a missing/``-1``/
  ``ANY_TAG`` tag is a *wildcard* recv (the dispatcher pattern);
- **dispatch tags**: ``== TAG_X`` / ``!= TAG_X`` / ``in (TAG_X, ...)``
  comparisons against ``TAG_``-named constants in a module that also has a
  wildcard recv — the tags its dispatch loop actually handles.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

ROLE_MARKER_RE = re.compile(
    r"#\s*mpit-analysis:\s*protocol-role\[\s*([A-Za-z0-9_]+)\s*->"
    r"\s*([A-Za-z0-9_]+)\s*\]"
)

_TAG_NAME_RE = re.compile(r"^TAG_[A-Z0-9_]+$")
_SEND_NAMES = {"send", "isend"}
_RECV_NAMES = {"recv", "irecv", "probe"}
_WILDCARD_NAMES = {"ANY_TAG"}


@dataclasses.dataclass(frozen=True)
class ProtoOp:
    """One protocol operation at one source location."""

    kind: str  # "send" | "recv" | "dispatch"
    tag: Optional[int]  # None = wildcard (recv only)
    tag_text: str  # the tag expression as written (for messages)
    rel: str
    line: int
    col: int
    symbol: str  # enclosing function qualname

    @property
    def is_wildcard(self) -> bool:
        return self.tag is None


@dataclasses.dataclass
class RoleModel:
    """The merged protocol surface of every module claiming one role."""

    role: str
    counterpart: str
    rels: list  # contributing module rel paths
    ops: list  # all ProtoOps

    @property
    def sends(self) -> list:
        return [op for op in self.ops if op.kind == "send"]

    @property
    def concrete_recvs(self) -> list:
        return [
            op
            for op in self.ops
            if op.kind == "recv" and not op.is_wildcard
        ]

    @property
    def has_wildcard_recv(self) -> bool:
        return any(
            op.kind == "recv" and op.is_wildcard for op in self.ops
        )

    @property
    def dispatch_tags(self) -> set:
        return {op.tag for op in self.ops if op.kind == "dispatch"}

    @property
    def sent_tags(self) -> set:
        return {op.tag for op in self.sends}

    @property
    def handled_tags(self) -> set:
        """Tags this role can consume: concrete recvs + dispatch branches."""
        return self.dispatch_tags | {
            op.tag for op in self.concrete_recvs
        }

    def sequences(self) -> dict:
        """Per enclosing function: its send/recv ops in source order (the
        input to the cross-wait check; dispatch ops are capabilities, not
        blocking points, and stay out)."""
        seqs: dict = {}
        for op in self.ops:
            if op.kind == "dispatch":
                continue
            seqs.setdefault((op.rel, op.symbol), []).append(op)
        for seq in seqs.values():
            seq.sort(key=lambda op: (op.line, op.col))
        return seqs


def module_role(source_lines) -> Optional[tuple]:
    """(role, counterpart) from the marker comment, or None. Only real
    COMMENT tokens count — a marker quoted in a docstring is not an
    opt-in (this module's own docstring shows one)."""
    for _, text in astutil.iter_comments(source_lines):
        m = ROLE_MARKER_RE.search(text)
        if m:
            return m.group(1), m.group(2)
    return None


def _tag_value(graph, info, node) -> tuple:
    """(resolved | None, is_wildcard). Unresolvable -> (None, False)."""
    if node is None:
        return None, True  # recv() default tag is ANY_TAG
    dotted = astutil.dotted_name(node)
    if dotted is not None and dotted.split(".")[-1] in _WILDCARD_NAMES:
        return None, True
    # the graph folds literal arithmetic AND resolves names through the
    # import graph, so ``TAG_BASE + 1`` and ``pserver.TAG_PARAM`` both
    # land on integers here
    val = graph.resolve_constant(info, node)
    if not isinstance(val, int) or isinstance(val, bool):
        return None, False
    if val == -1:
        return None, True
    return val, False


def _send_wrappers(tree: ast.Module) -> dict:
    """Module-local functions that forward a parameter into a transport
    send's tag slot: name -> index of that parameter in the call signature
    (``self`` excluded for methods — callers don't pass it).

    Computed to a fixpoint: a function forwarding its tag parameter into
    a *known wrapper* is itself a wrapper, so chains like
    ``PClient._scatter -> PClient._send_with_retry -> transport.send``
    still resolve their call sites' concrete tags."""
    out: dict = {}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in out:
                continue
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            call_params = params[1:] if params[:1] == ["self"] else params
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = astutil.call_last_name(sub)
                if callee in _SEND_NAMES:
                    if len(sub.args) + len(sub.keywords) < 3:
                        continue
                    tag_idx = 1
                elif callee in out and callee != node.name:
                    tag_idx = out[callee]
                else:
                    continue
                tag_arg = astutil.get_arg(sub, tag_idx, "tag")
                if (
                    isinstance(tag_arg, ast.Name)
                    and tag_arg.id in call_params
                ):
                    out[node.name] = call_params.index(tag_arg.id)
                    changed = True
                    break
    return out


def _op(mod, node, kind, tag, text) -> ProtoOp:
    return ProtoOp(
        kind=kind,
        tag=tag,
        tag_text=text,
        rel=mod.rel,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        symbol=astutil.enclosing_symbol(node, mod.parents),
    )


def _dispatch_tag_nodes(node: ast.Compare) -> Iterable:
    """TAG_*-named operands of an ==/!=/in comparison."""
    if not all(
        isinstance(op, (ast.Eq, ast.NotEq, ast.In)) for op in node.ops
    ):
        return
    for operand in (node.left, *node.comparators):
        cands = (
            operand.elts
            if isinstance(operand, (ast.Tuple, ast.List, ast.Set))
            else [operand]
        )
        for cand in cands:
            dotted = astutil.dotted_name(cand)
            if dotted and _TAG_NAME_RE.match(dotted.split(".")[-1]):
                yield cand, dotted


def extract_module_ops(mod, graph) -> list:
    """Every protocol op in one role module (tags graph-resolved)."""
    info = graph.module_for_rel(mod.rel)
    wrappers = _send_wrappers(mod.tree)
    ops: list = []
    saw_wildcard_recv = False
    dispatch_candidates: list = []
    for node in mod.nodes:
        if isinstance(node, ast.Compare):
            for cand, dotted in _dispatch_tag_nodes(node):
                val = graph.resolve_constant(info, dotted)
                if val is not None:
                    dispatch_candidates.append(
                        _op(mod, node, "dispatch", val, dotted)
                    )
            continue
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_last_name(node)
        if name in _SEND_NAMES:
            if len(node.args) + len(node.keywords) < 3:
                continue
            tag_arg = astutil.get_arg(node, 1, "tag")
            val, wild = _tag_value(graph, info, tag_arg)
            if val is not None and not wild:
                ops.append(
                    _op(mod, node, "send", val, ast.unparse(tag_arg))
                )
        elif name in _RECV_NAMES:
            tag_arg = astutil.get_arg(node, 1, "tag")
            val, wild = _tag_value(graph, info, tag_arg)
            if wild:
                saw_wildcard_recv = True
                ops.append(_op(mod, node, "recv", None, "ANY_TAG"))
            elif val is not None:
                ops.append(
                    _op(mod, node, "recv", val, ast.unparse(tag_arg))
                )
        elif name in wrappers:
            tag_arg = astutil.get_arg(node, wrappers[name], "tag")
            if tag_arg is None:
                continue
            val, wild = _tag_value(graph, info, tag_arg)
            if val is not None and not wild:
                ops.append(
                    _op(mod, node, "send", val, ast.unparse(tag_arg))
                )
    if saw_wildcard_recv:
        # dispatch branches only mean "handled" when a wildcard recv
        # actually routes messages into them
        ops.extend(dispatch_candidates)
    return ops


def extract_roles(project) -> dict:
    """role name -> RoleModel, merged over every marked module in scope."""
    graph = project.graph
    roles: dict = {}
    for mod in project.modules:
        # module_role tokenizes the whole source for comments — gate it
        # behind a cheap substring scan (the marker is a literal)
        if not any("protocol-role[" in ln for ln in mod.source_lines):
            continue
        marked = module_role(mod.source_lines)
        if marked is None:
            continue
        role, counterpart = marked
        model = roles.get(role)
        if model is None:
            model = roles[role] = RoleModel(
                role=role, counterpart=counterpart, rels=[], ops=[]
            )
        model.rels.append(mod.rel)
        model.ops.extend(extract_module_ops(mod, graph))
    return roles


# ---------------------------------------------------------------------------
# protocol *semantics* — the fault-tolerance machinery behind the tag model
#
# The role model above answers "which tags cross the wire"; the model
# checker (analysis/mcheck.py) additionally needs "what the protocol DOES
# about faults": whether FETCH attempt ids are echoed in the PARAM reply
# and checked by the client, whether the reply wait has a timeout escape,
# and the exact shape of the server's push dedup window. All of it is
# extracted syntactically from the same marked modules — recognized
# idioms, never imports — and anything that doesn't match a modeled idiom
# degrades conservatively (``None`` / opaque, meaning "don't check what
# you can't see").


@dataclasses.dataclass(frozen=True)
class DedupSemantics:
    """The server-side sliding dedup window, as written.

    Recognized shape (``_DedupWindow.admit`` in ``parallel/pserver.py``):
    a method literally named ``admit`` whose last parameter is the
    sequence number, rejecting on a boundary comparison against
    ``high - size`` plus a membership test on the seen-set.
    ``rejects_at_boundary`` is the off-by-one bit: ``seq <= high - size``
    (True, correct — a seq AT the boundary is rejected) vs ``seq <
    high - size`` (False — the boundary seq is re-admitted after the
    seen-set pruned past it, the classic window off-by-one)."""

    rel: str
    line: int
    col: int
    symbol: str
    rejects_at_boundary: bool
    checks_seen: bool
    prunes_seen: bool
    window_default: Optional[int]
    #: the window key is a tuple of several identity parameters (the
    #: ``key = (src, epoch)`` idiom) — a replacement client's fresh
    #: epoch gets a fresh window instead of inheriting its
    #: predecessor's seen-set; False = keyed by source only (or not
    #: at all), where a replacement's re-used seqs would be swallowed
    keyed_by_epoch: bool = False


@dataclasses.dataclass(frozen=True)
class ProtocolSemantics:
    """Everything the model checker needs about one client/server pair."""

    client_role: str
    server_role: str
    request_tag: int  # dispatch branch that sends the reply (FETCH)
    reply_tag: int  # server-sent, client-recv'd concretely (PARAM)
    push_tags: tuple  # dispatch branches feeding the dedup admit
    stop_tag: Optional[int]
    attempt_echoed: bool  # reply tuple carries the request's payload back
    attempt_checked: bool  # client compares the echoed id to the live one
    reply_recv_timeout: bool  # the reply recv can time out (retry escape)
    dedup: Optional[DedupSemantics]
    dedup_opaque: bool  # an admit exists but matches no modeled idiom
    reply_send: Optional[ProtoOp]  # anchors for findings
    reply_recv: Optional[ProtoOp]
    #: does the server's shard snapshot persist the dedup window next
    #: to the center+version (the crash-consistency idiom of
    #: ``_snapshot_state``)? True/False when a snapshot dict was found
    #: and classified; None = no snapshot machinery in the scan set
    #: (the model checker then skips restart schedules entirely)
    snapshot_includes_dedup: Optional[bool] = None
    #: does the server's shard HANDOFF (the reshard envelope that moves
    #: a shard's ownership to another server) ship the dedup window
    #: along with the shard data? True/False when handoff machinery was
    #: found and classified; None = no handoff machinery in the scan
    #: set (the model checker then skips the sharded configuration)
    handoff_includes_dedup: Optional[bool] = None

    @property
    def has_fault_machinery(self) -> bool:
        """Does this protocol *claim* fault tolerance? Only then is there
        anything for the model checker to verify — a bare request/reply
        fixture without attempt ids or dedup has no failure semantics,
        and flagging it for lacking them would drown MPT008's signal."""
        return self.attempt_echoed or self.dedup is not None


def _enclosing_function(node, parents):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def _is_transport_send(call: ast.Call) -> bool:
    return (
        astutil.call_last_name(call) in _SEND_NAMES
        and len(call.args) + len(call.keywords) >= 3
    )


def _classify_dispatch(server, by_rel, graph, reply_tag):
    """(request_tag, push_tags, stop_tag) from the server's dispatch Ifs:
    the branch that sends the reply is the request; branches feeding an
    ``admit``-named call are pushes; a branch recording the source in a
    set (``.add``) is the stop."""
    request_tag = None
    push_tags: set = set()
    stop_tag = None
    for rel in server.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        info = graph.module_for_rel(rel)
        for node in mod.nodes:
            if not isinstance(node, ast.If) or not isinstance(
                node.test, ast.Compare
            ):
                continue
            tags = []
            for _cand, dotted in _dispatch_tag_nodes(node.test):
                val = graph.resolve_constant(info, dotted)
                if val is not None:
                    tags.append(val)
            if not tags:
                continue
            body_calls = [
                sub
                for stmt in node.body
                for sub in ast.walk(stmt)
                if isinstance(sub, ast.Call)
            ]
            sends_reply = any(
                _is_transport_send(c)
                and _tag_value(
                    graph, info, astutil.get_arg(c, 1, "tag")
                )[0] == reply_tag
                for c in body_calls
            )
            calls_admit = any(
                "admit" in (astutil.call_last_name(c) or "")
                for c in body_calls
            )
            marks_stopped = any(
                astutil.call_last_name(c) == "add" for c in body_calls
            )
            for t in tags:
                if sends_reply:
                    if request_tag is None:
                        request_tag = t
                elif calls_admit:
                    push_tags.add(t)
                elif marks_stopped and stop_tag is None:
                    stop_tag = t
    return request_tag, push_tags, stop_tag


def _reply_is_echoed(server, by_rel, graph, reply_tag) -> bool:
    """Does the function sending the reply build a tuple containing the
    request's ``.payload`` (the attempt-id echo idiom)?"""
    for rel in server.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        info = graph.module_for_rel(rel)
        for node in mod.nodes:
            if not (
                isinstance(node, ast.Call) and _is_transport_send(node)
            ):
                continue
            val, _w = _tag_value(
                graph, info, astutil.get_arg(node, 1, "tag")
            )
            if val != reply_tag:
                continue
            scope = _enclosing_function(node, mod.parents) or mod.tree
            for sub in ast.walk(scope):
                if isinstance(sub, ast.Tuple) and any(
                    isinstance(e, ast.Attribute) and e.attr == "payload"
                    for e in sub.elts
                ):
                    return True
    return False


def _client_reply_handling(client, by_rel, graph, reply_tag):
    """(attempt_checked, reply_recv_timeout) from the client function(s)
    blocking on the reply tag: a ``timeout=`` argument on the recv is the
    deadlock escape; a Name-vs-Name ==/!= comparison in the same function
    is the attempt-id check (``got_id != attempt_id``)."""
    checked = False
    has_timeout = False
    for rel in client.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        info = graph.module_for_rel(rel)
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            if astutil.call_last_name(node) not in _RECV_NAMES:
                continue
            val, wild = _tag_value(
                graph, info, astutil.get_arg(node, 1, "tag")
            )
            if wild or val != reply_tag:
                continue
            to = astutil.get_arg(node, 2, "timeout")
            if to is not None and not (
                isinstance(to, ast.Constant) and to.value is None
            ):
                has_timeout = True
            scope = _enclosing_function(node, mod.parents) or mod.tree
            for sub in ast.walk(scope):
                if (
                    isinstance(sub, ast.Compare)
                    and len(sub.ops) == 1
                    and isinstance(sub.ops[0], (ast.Eq, ast.NotEq))
                    and isinstance(sub.left, ast.Name)
                    and isinstance(sub.comparators[0], ast.Name)
                ):
                    checked = True
    return checked, has_timeout


def _admit_window_default(fn, mod) -> Optional[int]:
    """The window-size default from the admit method's class ``__init__``
    (first non-self parameter), when statically visible."""
    cls = mod.parents.get(fn)
    while cls is not None and not isinstance(cls, ast.ClassDef):
        cls = mod.parents.get(cls)
    if cls is None:
        return None
    for node in cls.body:
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "__init__"
            and node.args.defaults
        ):
            return astutil.int_constant(node.args.defaults[-1])
    return None


def _extract_dedup(server, by_rel):
    """(DedupSemantics | None, found_admit). ``found_admit`` True with a
    None semantics means "there IS dedup machinery but it matches no
    modeled idiom" — the checker then assumes it correct rather than
    absent (resolve-or-skip, the graph's contract)."""
    for rel in server.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        for node in mod.nodes:
            if (
                not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                or node.name != "admit"
            ):
                continue
            params = [
                a.arg for a in node.args.posonlyargs + node.args.args
            ]
            if not params:
                continue
            seq = params[-1]
            rejects_at_boundary = None
            checks_seen = False
            anchor = node
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Compare) or len(sub.ops) != 1:
                    continue
                op = sub.ops[0]
                left, right = sub.left, sub.comparators[0]
                if (
                    isinstance(op, ast.In)
                    and isinstance(left, ast.Name)
                    and left.id == seq
                ):
                    checks_seen = True
                elif (
                    isinstance(op, (ast.Lt, ast.LtE))
                    and isinstance(left, ast.Name)
                    and left.id == seq
                    and isinstance(right, ast.BinOp)
                    and isinstance(right.op, ast.Sub)
                ):
                    rejects_at_boundary = isinstance(op, ast.LtE)
                    anchor = sub
                elif (  # mirrored form: high - size >= seq
                    isinstance(op, (ast.Gt, ast.GtE))
                    and isinstance(right, ast.Name)
                    and right.id == seq
                    and isinstance(left, ast.BinOp)
                    and isinstance(left.op, ast.Sub)
                ):
                    rejects_at_boundary = isinstance(op, ast.GtE)
                    anchor = sub
            if rejects_at_boundary is None:
                return None, True
            prunes = any(
                isinstance(sub, (ast.SetComp, ast.ListComp))
                for sub in ast.walk(node)
            )
            # the `key = (src, epoch)` idiom: a tuple of TWO OR MORE
            # identity parameters (the seq param excluded) built inside
            # admit means the window is keyed per client incarnation —
            # the property that keeps a replacement's re-used seqs from
            # being swallowed by its predecessor's window
            keyed = any(
                isinstance(sub, ast.Tuple)
                and len(sub.elts) >= 2
                and all(
                    isinstance(e, ast.Name)
                    and e.id in params
                    and e.id != seq
                    for e in sub.elts
                )
                for sub in ast.walk(node)
            )
            return (
                DedupSemantics(
                    rel=mod.rel,
                    line=anchor.lineno,
                    col=anchor.col_offset,
                    symbol=astutil.enclosing_symbol(anchor, mod.parents),
                    rejects_at_boundary=rejects_at_boundary,
                    checks_seen=checks_seen,
                    prunes_seen=prunes,
                    window_default=_admit_window_default(node, mod),
                    keyed_by_epoch=keyed,
                ),
                True,
            )
    return None, False


def _extract_snapshot_dedup(server, by_rel) -> Optional[bool]:
    """Does the server's shard-snapshot dict carry the dedup window next
    to the center and version counter? Recognized idiom: a server-role
    function whose name mentions ``persist`` or ``snapshot`` building a
    dict literal with string keys including both ``"center"`` and
    ``"version"`` — that dict IS the snapshot; the verdict is whether a
    ``"dedup"`` key rides in it. None when no such dict exists (no
    snapshot machinery — nothing for restart schedules to model)."""
    for rel in server.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        for node in mod.nodes:
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) or not (
                "persist" in node.name or "snapshot" in node.name
            ):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Dict):
                    continue
                keys = {
                    k.value
                    for k in sub.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
                if "center" in keys and "version" in keys:
                    return "dedup" in keys
    return None


def _extract_handoff_dedup(server, by_rel) -> Optional[bool]:
    """Does the server's shard-handoff path move the dedup window along
    with the shard data? Recognized idiom: server-role functions whose
    name mentions ``handoff`` or ``reshard`` — the send side extracts
    what travels, the receive side absorbs it — referencing the dedup
    machinery (any ``dedup``-named attribute or variable). True when
    any such function touches it, False when handoff functions exist
    but none does (exactly-once then dies at the ownership move), None
    when there is no handoff machinery at all."""
    found = None
    for rel in server.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        for node in mod.nodes:
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) or not (
                "handoff" in node.name or "reshard" in node.name
            ):
                continue
            mentions = any(
                "dedup"
                in (
                    sub.attr
                    if isinstance(sub, ast.Attribute)
                    else sub.id if isinstance(sub, ast.Name) else ""
                )
                for sub in ast.walk(node)
            )
            if mentions:
                return True
            found = False
    return found


# ---------------------------------------------------------------------------
# serving-fleet semantics — the router/replica routing protocol
#
# The fleet roles (mpit_tpu/fleet/) speak a different conversation from
# the PS pair: a ROUTE/REPLY request lane plus auxiliary weight-refresh
# and stop lanes. What the model checker needs from it is small: which
# tag pair is the request lane, whether the router's reply wait can time
# out (the death-detection escape), and whether a redispatch path exists
# (a router-role send of the route tag from a ``redispatch``-named
# function — the recovery idiom ``fleet/router.py`` carries). Extraction
# is recognized-idiom, resolve-or-skip, like everything above.


@dataclasses.dataclass(frozen=True)
class FleetSemantics:
    """Everything the fleet-route model checker needs."""

    router_role: str
    replica_role: str
    route_tag: int  # the request lane (lowest shared tag — see extract)
    reply_tag: int
    stop_tag: Optional[int]
    #: a router-role function whose name mentions ``redispatch`` re-sends
    #: the route tag — the orphan-recovery path exists
    redispatch_on_death: bool
    #: the router's reply recv carries a timeout (it can notice a dead
    #: replica instead of blocking forever)
    reply_recv_timeout: bool
    route_send: Optional[ProtoOp]  # finding anchor


def extract_fleet_semantics(project) -> Optional[FleetSemantics]:
    """The routed-serving pair's semantics, or None when the scan set has
    no replica-style role (a wildcard-recv dispatcher whose role name
    contains ``replica``) talking to a marked counterpart.

    Tag-pair selection: the request lane is the LOWEST router-sent tag
    the replica dispatches on, answered by the LOWEST replica-sent tag
    the router concretely recvs — the registry orders a protocol's
    request/reply lane before its auxiliary lanes (ROUTE=11/REPLY=12
    precede the weight lanes 13/14), and the rule keeps extraction
    deterministic without guessing at payload flow."""
    roles = project.roles
    replica = None
    for name in sorted(roles):
        cand = roles[name]
        if (
            "replica" in name
            and cand.has_wildcard_recv
            and roles.get(cand.counterpart) is not None
        ):
            replica = cand
            break
    if replica is None:
        return None
    router = roles[replica.counterpart]
    route_cands = sorted(
        t for t in (router.sent_tags & replica.dispatch_tags)
        if t is not None
    )
    reply_cands = sorted(
        t for t in (
            replica.sent_tags
            & {op.tag for op in router.concrete_recvs}
        )
        if t is not None
    )
    if not route_cands or not reply_cands:
        return None
    route_tag, reply_tag = route_cands[0], reply_cands[0]

    by_rel = {m.rel: m for m in project.modules}
    graph = project.graph
    # the stop lane: a replica dispatch branch whose body sets a
    # ``stop``-named attribute (``self.stopped = True``)
    stop_tag = None
    for rel in replica.rels:
        mod = by_rel.get(rel)
        if mod is None:
            continue
        info = graph.module_for_rel(rel)
        for node in mod.nodes:
            if not isinstance(node, ast.If) or not isinstance(
                node.test, ast.Compare
            ):
                continue
            tags = [
                graph.resolve_constant(info, dotted)
                for _c, dotted in _dispatch_tag_nodes(node.test)
            ]
            tags = [t for t in tags if t is not None]
            if not tags:
                continue
            sets_stop = any(
                isinstance(sub, ast.Assign)
                and any(
                    isinstance(t, ast.Attribute) and "stop" in t.attr
                    for t in sub.targets
                )
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if sets_stop and stop_tag is None:
                stop_tag = tags[0]
    redispatch = any(
        op.tag == route_tag and "redispatch" in op.symbol
        for op in router.sends
    )
    _checked, reply_recv_timeout = _client_reply_handling(
        router, by_rel, graph, reply_tag
    )
    route_send = min(
        (op for op in router.sends if op.tag == route_tag),
        key=lambda op: (op.rel, op.line, op.col),
        default=None,
    )
    return FleetSemantics(
        router_role=router.role,
        replica_role=replica.role,
        route_tag=route_tag,
        reply_tag=reply_tag,
        stop_tag=stop_tag,
        redispatch_on_death=redispatch,
        reply_recv_timeout=reply_recv_timeout,
        route_send=route_send,
    )


def extract_semantics(project) -> Optional[ProtocolSemantics]:
    """The modeled client/server pair's fault semantics, or None when the
    scan set has no recognizable request/reply protocol (no role pair, no
    unique reply tag, or no dispatch branch answering a request)."""
    roles = project.roles
    client = server = None
    for name in sorted(roles):
        cand = roles[name]
        cp = roles.get(cand.counterpart)
        if cp is None or not cand.has_wildcard_recv:
            continue
        client, server = cp, cand
        break
    if server is None:
        return None
    reply_tags = server.sent_tags & {
        op.tag for op in client.concrete_recvs
    }
    if len(reply_tags) != 1:
        return None
    reply_tag = next(iter(reply_tags))

    by_rel = {m.rel: m for m in project.modules}
    graph = project.graph
    request_tag, push_tags, stop_tag = _classify_dispatch(
        server, by_rel, graph, reply_tag
    )
    if request_tag is None or request_tag not in client.sent_tags:
        return None
    attempt_echoed = _reply_is_echoed(server, by_rel, graph, reply_tag)
    attempt_checked, reply_recv_timeout = _client_reply_handling(
        client, by_rel, graph, reply_tag
    )
    dedup, found_admit = _extract_dedup(server, by_rel)

    def _first(ops):
        return min(ops, key=lambda op: (op.rel, op.line, op.col), default=None)

    return ProtocolSemantics(
        client_role=client.role,
        server_role=server.role,
        request_tag=request_tag,
        reply_tag=reply_tag,
        push_tags=tuple(sorted(push_tags)),
        stop_tag=stop_tag,
        attempt_echoed=attempt_echoed,
        attempt_checked=attempt_checked,
        reply_recv_timeout=reply_recv_timeout,
        dedup=dedup,
        dedup_opaque=found_admit and dedup is None,
        reply_send=_first(
            [op for op in server.sends if op.tag == reply_tag]
        ),
        reply_recv=_first(
            [op for op in client.concrete_recvs if op.tag == reply_tag]
        ),
        snapshot_includes_dedup=_extract_snapshot_dedup(server, by_rel),
        handoff_includes_dedup=_extract_handoff_dedup(server, by_rel),
    )
