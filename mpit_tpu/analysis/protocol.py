"""Protocol-role model: per-role send/recv tag sequences, statically.

The host-async PS protocol is a conversation between two roles — the
pserver's wildcard-recv dispatch loop and the pclient's send/recv call
pattern — and its hardest failure class is cross-rank: a tag one role sends
that the counterpart never receives (the message parks forever and teardown
hangs), or both roles blocking in recv for a tag only the *other* side's
later send would satisfy. Rank-local lint rules cannot see either; this
module extracts the static halves from the AST so MPT008 can.

A module opts into a role with a marker comment anywhere at the top level::

    # mpit-analysis: protocol-role[client->server]

meaning "this module implements role ``client``, whose counterpart role is
``server``". Several modules may share one role (``pclient.py`` and
``ps_roles.py`` are both ``client``); their operations merge. The markers
live with the code — ``parallel/pserver.py``, ``parallel/pclient.py`` and
``parallel/ps_roles.py`` carry them — so the model needs no path
configuration and fixture packages participate the same way.

Extracted per role, with tags resolved to integers through the module graph
(``TAG_PARAM`` imported from ``pserver`` resolves to 4; unresolvable tag
expressions are skipped — conservative, no finding):

- **sends**: ``send``/``isend`` call sites (3+ args: the transport shape),
  including module-local indirection to a fixpoint — a function that
  forwards a tag parameter toward a transport send, directly
  (``PClient._send_with_retry``) or through another wrapper
  (``PClient._scatter`` riding the retry helper), counts its call sites
  (``self._scatter(TAG_PUSH_EASGD, ...)``) as sends of the resolved tag;
- **recvs**: ``recv``/``irecv``/``probe`` sites; a missing/``-1``/
  ``ANY_TAG`` tag is a *wildcard* recv (the dispatcher pattern);
- **dispatch tags**: ``== TAG_X`` / ``!= TAG_X`` / ``in (TAG_X, ...)``
  comparisons against ``TAG_``-named constants in a module that also has a
  wildcard recv — the tags its dispatch loop actually handles.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

ROLE_MARKER_RE = re.compile(
    r"#\s*mpit-analysis:\s*protocol-role\[\s*([A-Za-z0-9_]+)\s*->"
    r"\s*([A-Za-z0-9_]+)\s*\]"
)

_TAG_NAME_RE = re.compile(r"^TAG_[A-Z0-9_]+$")
_SEND_NAMES = {"send", "isend"}
_RECV_NAMES = {"recv", "irecv", "probe"}
_WILDCARD_NAMES = {"ANY_TAG"}


@dataclasses.dataclass(frozen=True)
class ProtoOp:
    """One protocol operation at one source location."""

    kind: str  # "send" | "recv" | "dispatch"
    tag: Optional[int]  # None = wildcard (recv only)
    tag_text: str  # the tag expression as written (for messages)
    rel: str
    line: int
    col: int
    symbol: str  # enclosing function qualname

    @property
    def is_wildcard(self) -> bool:
        return self.tag is None


@dataclasses.dataclass
class RoleModel:
    """The merged protocol surface of every module claiming one role."""

    role: str
    counterpart: str
    rels: list  # contributing module rel paths
    ops: list  # all ProtoOps

    @property
    def sends(self) -> list:
        return [op for op in self.ops if op.kind == "send"]

    @property
    def concrete_recvs(self) -> list:
        return [
            op
            for op in self.ops
            if op.kind == "recv" and not op.is_wildcard
        ]

    @property
    def has_wildcard_recv(self) -> bool:
        return any(
            op.kind == "recv" and op.is_wildcard for op in self.ops
        )

    @property
    def dispatch_tags(self) -> set:
        return {op.tag for op in self.ops if op.kind == "dispatch"}

    @property
    def sent_tags(self) -> set:
        return {op.tag for op in self.sends}

    @property
    def handled_tags(self) -> set:
        """Tags this role can consume: concrete recvs + dispatch branches."""
        return self.dispatch_tags | {
            op.tag for op in self.concrete_recvs
        }

    def sequences(self) -> dict:
        """Per enclosing function: its send/recv ops in source order (the
        input to the cross-wait check; dispatch ops are capabilities, not
        blocking points, and stay out)."""
        seqs: dict = {}
        for op in self.ops:
            if op.kind == "dispatch":
                continue
            seqs.setdefault((op.rel, op.symbol), []).append(op)
        for seq in seqs.values():
            seq.sort(key=lambda op: (op.line, op.col))
        return seqs


def module_role(source_lines) -> Optional[tuple]:
    """(role, counterpart) from the marker comment, or None. Only real
    COMMENT tokens count — a marker quoted in a docstring is not an
    opt-in (this module's own docstring shows one)."""
    for _, text in astutil.iter_comments(source_lines):
        m = ROLE_MARKER_RE.search(text)
        if m:
            return m.group(1), m.group(2)
    return None


def _tag_value(graph, info, node) -> tuple:
    """(resolved | None, is_wildcard). Unresolvable -> (None, False)."""
    if node is None:
        return None, True  # recv() default tag is ANY_TAG
    val = astutil.int_constant(node)
    if val is None:
        dotted = astutil.dotted_name(node)
        if dotted is not None:
            if dotted.split(".")[-1] in _WILDCARD_NAMES:
                return None, True
            val = graph.resolve_constant(info, dotted)
    if val == -1:
        return None, True
    return val, False


def _send_wrappers(tree: ast.Module) -> dict:
    """Module-local functions that forward a parameter into a transport
    send's tag slot: name -> index of that parameter in the call signature
    (``self`` excluded for methods — callers don't pass it).

    Computed to a fixpoint: a function forwarding its tag parameter into
    a *known wrapper* is itself a wrapper, so chains like
    ``PClient._scatter -> PClient._send_with_retry -> transport.send``
    still resolve their call sites' concrete tags."""
    out: dict = {}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in out:
                continue
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            call_params = params[1:] if params[:1] == ["self"] else params
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = astutil.call_last_name(sub)
                if callee in _SEND_NAMES:
                    if len(sub.args) + len(sub.keywords) < 3:
                        continue
                    tag_idx = 1
                elif callee in out and callee != node.name:
                    tag_idx = out[callee]
                else:
                    continue
                tag_arg = astutil.get_arg(sub, tag_idx, "tag")
                if (
                    isinstance(tag_arg, ast.Name)
                    and tag_arg.id in call_params
                ):
                    out[node.name] = call_params.index(tag_arg.id)
                    changed = True
                    break
    return out


def _op(mod, node, kind, tag, text) -> ProtoOp:
    return ProtoOp(
        kind=kind,
        tag=tag,
        tag_text=text,
        rel=mod.rel,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        symbol=astutil.enclosing_symbol(node, mod.parents),
    )


def _dispatch_tag_nodes(node: ast.Compare) -> Iterable:
    """TAG_*-named operands of an ==/!=/in comparison."""
    if not all(
        isinstance(op, (ast.Eq, ast.NotEq, ast.In)) for op in node.ops
    ):
        return
    for operand in (node.left, *node.comparators):
        cands = (
            operand.elts
            if isinstance(operand, (ast.Tuple, ast.List, ast.Set))
            else [operand]
        )
        for cand in cands:
            dotted = astutil.dotted_name(cand)
            if dotted and _TAG_NAME_RE.match(dotted.split(".")[-1]):
                yield cand, dotted


def extract_module_ops(mod, graph) -> list:
    """Every protocol op in one role module (tags graph-resolved)."""
    info = graph.module_for_rel(mod.rel)
    wrappers = _send_wrappers(mod.tree)
    ops: list = []
    saw_wildcard_recv = False
    dispatch_candidates: list = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Compare):
            for cand, dotted in _dispatch_tag_nodes(node):
                val = graph.resolve_constant(info, dotted)
                if val is not None:
                    dispatch_candidates.append(
                        _op(mod, node, "dispatch", val, dotted)
                    )
            continue
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_last_name(node)
        if name in _SEND_NAMES:
            if len(node.args) + len(node.keywords) < 3:
                continue
            tag_arg = astutil.get_arg(node, 1, "tag")
            val, wild = _tag_value(graph, info, tag_arg)
            if val is not None and not wild:
                ops.append(
                    _op(mod, node, "send", val, ast.unparse(tag_arg))
                )
        elif name in _RECV_NAMES:
            tag_arg = astutil.get_arg(node, 1, "tag")
            val, wild = _tag_value(graph, info, tag_arg)
            if wild:
                saw_wildcard_recv = True
                ops.append(_op(mod, node, "recv", None, "ANY_TAG"))
            elif val is not None:
                ops.append(
                    _op(mod, node, "recv", val, ast.unparse(tag_arg))
                )
        elif name in wrappers:
            tag_arg = astutil.get_arg(node, wrappers[name], "tag")
            if tag_arg is None:
                continue
            val, wild = _tag_value(graph, info, tag_arg)
            if val is not None and not wild:
                ops.append(
                    _op(mod, node, "send", val, ast.unparse(tag_arg))
                )
    if saw_wildcard_recv:
        # dispatch branches only mean "handled" when a wildcard recv
        # actually routes messages into them
        ops.extend(dispatch_candidates)
    return ops


def extract_roles(project) -> dict:
    """role name -> RoleModel, merged over every marked module in scope."""
    graph = project.graph
    roles: dict = {}
    for mod in project.modules:
        marked = module_role(mod.source_lines)
        if marked is None:
            continue
        role, counterpart = marked
        model = roles.get(role)
        if model is None:
            model = roles[role] = RoleModel(
                role=role, counterpart=counterpart, rels=[], ops=[]
            )
        model.rels.append(mod.rel)
        model.ops.extend(extract_module_ops(mod, graph))
    return roles
