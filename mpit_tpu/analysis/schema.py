"""Whole-program payload-schema inference for the wire protocol.

The role model (:mod:`mpit_tpu.analysis.protocol`) answers *which tags*
cross the wire; this pass answers *what rides inside them*. For every
wire tag it collects

- **sender construction sites**: the payload expression at each transport
  ``send``/``isend`` (and each call into the module-local send-wrapper
  chains MPT004/MPT008 already track), classified into a small kind
  lattice — ``none``/``bool``/``int``/``float``/``str``/``bytes``/
  ``ndarray``/``quant``/``list``, tuple shapes with per-field kind sets,
  ``unencodable:<what>`` for anything that falls off ``encode_frame``
  onto the per-message pickle fallback, and ``unknown`` when resolution
  fails (resolve-or-skip: no claim beats a wrong claim);
- **receiver consumption sites**: for each dispatch branch of a
  wildcard-recv loop (``if msg.tag == TAG_X:``) and each concrete-tag
  recv, the unpacking patterns (``a, b, c = msg.payload``), arity checks
  (``len(payload) == 4``), ``isinstance`` acceptances, constant index
  subscripts, ``payload is None`` guards, and opaque uses — followed
  through module-local helper calls (``self._admit_push(msg)``).

The unified per-tag table is the input to three rules
(:mod:`mpit_tpu.analysis.rules.payload_schema`): MPT016
sender/receiver shape divergence, MPT017 pickle-fallback payloads, and
MPT018 snapshot schema drift (``save_shard_state`` writes vs restore
reads). It is also what ``python -m mpit_tpu.analysis schema`` renders
and what ``wire-schema.lock.json`` pins: protocol-shape changes must be
*declared* with ``--update-lock``, or lint gate 9 fails.

Everything here is stdlib-only and purely syntactic — scanned code is
parsed, never imported.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from mpit_tpu.analysis import astutil, protocol

SCHEMA_LOCK_FILENAME = "wire-schema.lock.json"
SCHEMA_LOCK_VERSION = 1

#: kind-resolution recursion bound (alias/attr chains; also the cycle guard)
MAX_CLASSIFY_DEPTH = 8
#: how deep receiver analysis follows module-local helper calls
MAX_HELPER_DEPTH = 3

_TAG_NAME_RE = re.compile(r"^TAG_[A-Z0-9_]+$")

UNKNOWN: FrozenSet = frozenset({"unknown"})

#: numpy constructors whose result is an ndarray (classification only —
#: the wire codec accepts any ndarray of its registered dtypes)
_NDARRAY_FACTORIES = {
    "asarray",
    "array",
    "ascontiguousarray",
    "arange",
    "concatenate",
    "empty",
    "empty_like",
    "frombuffer",
    "full",
    "ones",
    "ones_like",
    "stack",
    "zeros",
    "zeros_like",
}

#: isinstance() type name (last dotted component) -> payload kind
_ISINSTANCE_KINDS = {
    "bool": "bool",
    "bytes": "bytes",
    "dict": "unencodable:dict",
    "float": "float",
    "int": "int",
    "list": "list",
    "ndarray": "ndarray",
    "QuantArray": "quant",
    "set": "unencodable:set",
    "str": "str",
    "tuple": "tuple",
}


# ---------------------------------------------------------------------------
# data model


@dataclasses.dataclass(frozen=True)
class Site:
    """One source location, line-anchored for findings and the CLI dump."""

    rel: str
    line: int
    col: int
    symbol: str


def _site(mod, node) -> Site:
    return Site(
        rel=mod.rel,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        symbol=astutil.enclosing_symbol(node, mod.parents),
    )


@dataclasses.dataclass(frozen=True)
class SenderShape:
    """One possible payload shape at one sender site. A site whose
    classification is a union (``reply`` assigned in three branches)
    contributes one shape per branch."""

    tag: int
    shape: object  # kind string, or ("tuple", (kindset, ...))
    site: Site
    text: str  # flagged source line (finding fingerprint stability)


@dataclasses.dataclass
class TagRecv:
    """Everything one tag's receivers were seen to accept."""

    none_sites: List[Site] = dataclasses.field(default_factory=list)
    any_sites: List[Site] = dataclasses.field(default_factory=list)
    ignored_sites: List[Site] = dataclasses.field(default_factory=list)
    tuple_any: List[Site] = dataclasses.field(default_factory=list)
    # accepted arity -> {field index: set of accepted kinds}
    arities: Dict[int, Dict[int, Set[str]]] = dataclasses.field(
        default_factory=dict
    )
    arity_sites: Dict[int, Site] = dataclasses.field(default_factory=dict)
    # constant-index subscript reads outside arity checks
    field_reads: Dict[int, Site] = dataclasses.field(default_factory=dict)
    # scalar isinstance acceptances: kind -> site
    kinds: Dict[str, Site] = dataclasses.field(default_factory=dict)

    @property
    def constrained(self) -> bool:
        return bool(
            self.none_sites
            or self.tuple_any
            or self.arities
            or self.kinds
            or self.field_reads
        )

    @property
    def opaque(self) -> bool:
        """Some path consumes the payload without shape constraints —
        every sender shape is then admissible (conservative)."""
        return bool(self.any_sites or self.ignored_sites)


@dataclasses.dataclass(frozen=True)
class PayloadSite:
    """One classified send payload (every module, tag not required) —
    the MPT017 input."""

    site: Site
    kinds: FrozenSet
    text: str


@dataclasses.dataclass
class SchemaModel:
    tag_names: Dict[int, str] = dataclasses.field(default_factory=dict)
    senders: Dict[int, List[SenderShape]] = dataclasses.field(
        default_factory=dict
    )
    receivers: Dict[int, TagRecv] = dataclasses.field(default_factory=dict)
    payload_sites: List[PayloadSite] = dataclasses.field(
        default_factory=list
    )
    snapshot_writes: Dict[str, Site] = dataclasses.field(
        default_factory=dict
    )
    snapshot_reads: Dict[str, Site] = dataclasses.field(default_factory=dict)

    def tag_name(self, tag: int) -> str:
        return self.tag_names.get(tag, f"tag {tag}")

    def to_json(self) -> dict:
        tags = sorted(set(self.senders) | set(self.receivers))
        doc: dict = {"version": SCHEMA_LOCK_VERSION, "tags": {}}
        for tag in tags:
            sender = sorted(
                {kind_repr(s.shape) for s in self.senders.get(tag, ())}
            )
            receiver = receiver_repr(self.receivers.get(tag))
            doc["tags"][str(tag)] = {
                "name": self.tag_names.get(tag, ""),
                "sender": sender,
                "receiver": receiver,
                "precision": tag_precision(sender, receiver),
            }
        doc["snapshot"] = {
            "writes": sorted(self.snapshot_writes),
            "reads": sorted(self.snapshot_reads),
        }
        return doc


def tag_precision(sender_reprs, receiver_reprs) -> list:
    """The per-tag payload precision column (the MPT022 wire-drift
    anchor): ``"codes"`` when any modeled shape on either side carries
    quantized codes (a ``quant`` kind — QuantArray in transit), ``"f32"``
    when raw float32 ndarrays ride the tag. Control tags get ``[]``.
    Derived from the same kind strings the lock already pins, so a PR
    that flips a tag between raw and quantized payloads shows up as a
    one-line lock diff — the lockfile, not prose, is the authority."""
    blob = " ".join(list(sender_reprs) + list(receiver_reprs))
    out = []
    if "quant" in blob:
        out.append("codes")
    if "ndarray" in blob:
        out.append("f32")
    return out


def is_tuple_kind(kind) -> bool:
    return isinstance(kind, tuple) and kind and kind[0] == "tuple"


def kind_repr(kind) -> str:
    if is_tuple_kind(kind):
        return "(" + ", ".join(kindset_repr(fs) for fs in kind[1]) + ")"
    return "?" if kind == "unknown" else str(kind)


def kindset_repr(kinds) -> str:
    if not kinds:
        return "?"
    return "|".join(sorted(kind_repr(k) for k in kinds))


def receiver_repr(rec: Optional[TagRecv]) -> List[str]:
    if rec is None:
        return []
    out: Set[str] = set()
    if rec.none_sites:
        out.add("none")
    if rec.any_sites:
        out.add("any")
    if rec.ignored_sites:
        out.add("ignored")
    if rec.tuple_any:
        out.add("tuple")
    for k in rec.arities:
        fields = rec.arities[k]
        parts = [
            kindset_repr(frozenset(fields[i])) if fields.get(i) else "?"
            for i in range(k)
        ]
        out.add(f"tuple{k}({', '.join(parts)})")
    for kind in rec.kinds:
        out.add(kind_repr(kind))
    covered = max(rec.arities, default=0)
    for i in rec.field_reads:
        if i >= covered:
            out.add(f"field[{i}]")
    return sorted(out)


# ---------------------------------------------------------------------------
# expression -> kind classification


class _Classifier:
    """Per-module payload-kind resolution: local assignment chains,
    ``self.X`` attribute assignments anywhere in the class, and
    module-level bindings (through the module graph's info), to a depth
    bound. Anything unmodeled is ``unknown`` — never a guess."""

    def __init__(self, mod, info, class_names: Set[str]):
        self.mod = mod
        self.info = info  # graph ModuleInfo (module-level bindings)
        self.class_names = class_names
        self._fn_assigns: dict = {}
        self._attr_assigns: Optional[dict] = None

    # -- binding collection

    def _collect_scope(self, stmts, out: dict) -> None:
        """Name bindings in a statement list, NOT descending into nested
        def/class scopes. A non-Assign binding (loop target, with-as,
        augmented) records ``None`` = unknown."""
        for stmt in stmts:
            if isinstance(
                stmt,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out.setdefault(tgt.id, []).append(node.value)
                        elif isinstance(tgt, (ast.Tuple, ast.List)):
                            for e in tgt.elts:
                                if isinstance(e, ast.Name):
                                    out.setdefault(e.id, []).append(None)
                elif isinstance(node, ast.AnnAssign):
                    if isinstance(node.target, ast.Name):
                        out.setdefault(node.target.id, []).append(
                            node.value
                        )
                elif isinstance(node, ast.AugAssign):
                    if isinstance(node.target, ast.Name):
                        out.setdefault(node.target.id, []).append(None)
                elif isinstance(node, ast.NamedExpr):
                    if isinstance(node.target, ast.Name):
                        out.setdefault(node.target.id, []).append(
                            node.value
                        )
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    for e in ast.walk(node.target):
                        if isinstance(e, ast.Name):
                            out.setdefault(e.id, []).append(None)
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if item.optional_vars is not None:
                            for e in ast.walk(item.optional_vars):
                                if isinstance(e, ast.Name):
                                    out.setdefault(e.id, []).append(None)

    def fn_assigns(self, fn) -> dict:
        key = id(fn) if fn is not None else None
        cached = self._fn_assigns.get(key)
        if cached is None:
            cached = {}
            if fn is not None:
                self._collect_scope(fn.body, cached)
            self._fn_assigns[key] = cached
        return cached

    def attr_assigns(self) -> dict:
        if self._attr_assigns is None:
            out: dict = {}
            for node in self.mod.nodes:
                if isinstance(node, ast.Assign):
                    value = node.value
                    targets = node.targets
                elif isinstance(node, ast.AugAssign):
                    value = None
                    targets = [node.target]
                else:
                    continue
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        fn = protocol._enclosing_function(
                            node, self.mod.parents
                        )
                        out.setdefault(tgt.attr, []).append((value, fn))
            self._attr_assigns = out
        return self._attr_assigns

    # -- classification

    def classify(self, node, fn, depth=0, seen=frozenset()) -> FrozenSet:
        if node is None or depth > MAX_CLASSIFY_DEPTH:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            v = node.value
            if v is None:
                return frozenset({"none"})
            if isinstance(v, bool):
                return frozenset({"bool"})
            if isinstance(v, int):
                return frozenset({"int"})
            if isinstance(v, float):
                return frozenset({"float"})
            if isinstance(v, str):
                return frozenset({"str"})
            if isinstance(v, bytes):
                return frozenset({"bytes"})
            return UNKNOWN
        if isinstance(node, ast.Tuple):
            if any(isinstance(e, ast.Starred) for e in node.elts):
                return UNKNOWN
            fields = tuple(
                self.classify(e, fn, depth + 1, seen) for e in node.elts
            )
            return frozenset({("tuple", fields)})
        if isinstance(node, (ast.List, ast.ListComp)):
            return frozenset({"list"})
        if isinstance(node, (ast.Dict, ast.DictComp)):
            return frozenset({"unencodable:dict"})
        if isinstance(node, (ast.Set, ast.SetComp)):
            return frozenset({"unencodable:set"})
        if isinstance(node, (ast.GeneratorExp, ast.Lambda)):
            return frozenset({"unencodable:" + type(node).__name__.lower()})
        if isinstance(node, ast.IfExp):
            return self.classify(
                node.body, fn, depth + 1, seen
            ) | self.classify(node.orelse, fn, depth + 1, seen)
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand, fn, depth + 1, seen)
        if isinstance(node, ast.JoinedStr):
            return frozenset({"str"})
        if isinstance(node, ast.Name):
            return self._classify_name(node.id, fn, depth, seen)
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return self._classify_attr(node.attr, depth, seen)
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            return self._classify_subscript(node, fn, depth, seen)
        if isinstance(node, ast.BinOp):
            left = self.classify(node.left, fn, depth + 1, seen)
            right = self.classify(node.right, fn, depth + 1, seen)
            if "ndarray" in left or "ndarray" in right:
                return frozenset({"ndarray"})
            if left <= {"int", "bool"} and right <= {"int", "bool"}:
                return frozenset({"int"})
            if left <= {"int", "float", "bool"} and right <= {
                "int",
                "float",
                "bool",
            }:
                return frozenset({"float"})
            return UNKNOWN
        if isinstance(node, ast.Call):
            return self._classify_call(node, fn, depth, seen)
        return UNKNOWN

    def _classify_name(self, name, fn, depth, seen) -> FrozenSet:
        key = ("name", name, id(fn) if fn is not None else None)
        if key in seen:
            return UNKNOWN
        seen = seen | {key}
        exprs = self.fn_assigns(fn).get(name) if fn is not None else None
        scope_fn = fn
        if not exprs:
            # fall through to module-level bindings (the graph's view)
            if self.info is not None and name in self.info.constants:
                return self._const_kind(self.info.constants[name])
            if self.info is not None and name in self.info.assigns:
                exprs = [self.info.assigns[name]]
                scope_fn = None
            else:
                return UNKNOWN
        out: Set = set()
        for e in exprs:
            if e is None:
                out |= UNKNOWN
            else:
                out |= self.classify(e, scope_fn, depth + 1, seen)
        return frozenset(out) if out else UNKNOWN

    @staticmethod
    def _const_kind(value) -> FrozenSet:
        if value is None:
            return frozenset({"none"})
        if isinstance(value, bool):
            return frozenset({"bool"})
        if isinstance(value, int):
            return frozenset({"int"})
        if isinstance(value, float):
            return frozenset({"float"})
        if isinstance(value, str):
            return frozenset({"str"})
        if isinstance(value, bytes):
            return frozenset({"bytes"})
        return UNKNOWN

    def _classify_attr(self, attr, depth, seen) -> FrozenSet:
        key = ("attr", attr)
        if key in seen:
            return UNKNOWN
        entries = self.attr_assigns().get(attr)
        if not entries:
            return UNKNOWN
        seen = seen | {key}
        out: Set = set()
        for expr, afn in entries:
            if expr is None:
                out |= UNKNOWN
            else:
                out |= self.classify(expr, afn, depth + 1, seen)
        return frozenset(out) if out else UNKNOWN

    def _classify_subscript(self, node, fn, depth, seen) -> FrozenSet:
        base = self.classify(node.value, fn, depth + 1, seen)
        out: Set = set()
        for k in base:
            if k == "ndarray":
                out.add("ndarray")  # index or slice of an array: array
            elif is_tuple_kind(k):
                idx = astutil.int_constant(node.slice)
                if idx is not None and 0 <= idx < len(k[1]):
                    out |= k[1][idx]
                else:
                    out.add("unknown")
            else:
                out.add("unknown")
        return frozenset(out) if out else UNKNOWN

    def _classify_call(self, node, fn, depth, seen) -> FrozenSet:
        name = astutil.call_last_name(node)
        dotted = astutil.dotted_name(node.func)
        if name in ("quantize", "QuantArray"):
            return frozenset({"quant"})
        if name == "dequantize":
            return frozenset({"ndarray"})
        if (
            dotted
            and dotted.split(".")[0] in ("np", "numpy")
            and name in _NDARRAY_FACTORIES
        ):
            return frozenset({"ndarray"})
        if isinstance(node.func, ast.Attribute):
            if name == "astype":
                return frozenset({"ndarray"})
            if name == "copy" and not node.args:
                return self.classify(node.func.value, fn, depth + 1, seen)
            if name == "get" and len(node.args) == 2:
                return self.classify(node.args[1], fn, depth + 1, seen)
        if name == "from_bytes":
            return frozenset({"int"})
        if dotted in ("itertools.count", "count"):
            return frozenset({"_int_iter"})
        if name == "next" and node.args:
            inner = self.classify(node.args[0], fn, depth + 1, seen)
            return (
                frozenset({"int"}) if "_int_iter" in inner else UNKNOWN
            )
        if dotted in ("int", "len"):
            return frozenset({"int"})
        if dotted == "float":
            return frozenset({"float"})
        if dotted == "str":
            return frozenset({"str"})
        if dotted == "bytes":
            return frozenset({"bytes"})
        if dotted == "bool":
            return frozenset({"bool"})
        if (
            name in self.class_names
            and name != "QuantArray"
            and dotted == name  # a bare constructor call, not a method
        ):
            return frozenset({f"unencodable:{name}"})
        return UNKNOWN


# ---------------------------------------------------------------------------
# sender extraction


def _wrapper_payload_info(mod, wrappers: dict) -> dict:
    """For each send wrapper: (call-frame index of the forwarded payload
    parameter, its name) — or (None, None) when the wrapper constructs
    the payload itself (``_scatter`` building the push tuple), in which
    case its *inner* call is the construction site and the wrapper's own
    call sites carry no payload expression."""
    out: dict = {}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(mod.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if node.name not in wrappers or node.name in out:
                continue
            params = [
                a.arg for a in node.args.posonlyargs + node.args.args
            ]
            call_params = params[1:] if params[:1] == ["self"] else params
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = astutil.call_last_name(sub)
                if (
                    callee in protocol._SEND_NAMES
                    and len(sub.args) + len(sub.keywords) >= 3
                ):
                    pay = astutil.get_arg(sub, 2, "payload")
                elif callee in wrappers and callee != node.name:
                    if callee not in out:
                        continue  # resolved on a later fixpoint round
                    ppos = out[callee][0]
                    if ppos is None:
                        pay = None
                    else:
                        pay = astutil.get_arg(sub, ppos, "payload")
                else:
                    continue
                if isinstance(pay, ast.Name) and pay.id in call_params:
                    out[node.name] = (call_params.index(pay.id), pay.id)
                else:
                    out[node.name] = (None, None)
                changed = True
                break
    for name in wrappers:
        out.setdefault(name, (None, None))
    return out


def _fn_call_params(fn) -> list:
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    return params[1:] if params[:1] == ["self"] else params


def _incoming_tags(mod, graph, info, wrappers: dict) -> dict:
    """Concrete tag values flowing into each wrapper from its call
    sites, to a fixpoint — ``_send_with_retry`` called from ``_scatter``
    with ``_scatter``'s own tag parameter inherits ``_scatter``'s
    incoming set (``{TAG_PUSH_EASGD, TAG_PUSH_DELTA}``)."""
    incoming = {name: set() for name in wrappers}
    changed = True
    while changed:
        changed = False
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            callee = astutil.call_last_name(node)
            if callee not in wrappers:
                continue
            tag_arg = astutil.get_arg(node, wrappers[callee], "tag")
            if tag_arg is None:
                continue
            val, wild = protocol._tag_value(graph, info, tag_arg)
            add: set = set()
            if val is not None:
                add = {val}
            elif isinstance(tag_arg, ast.Name):
                encl = protocol._enclosing_function(node, mod.parents)
                if encl is not None and encl.name in wrappers:
                    cp = _fn_call_params(encl)
                    ti = wrappers[encl.name]
                    if ti < len(cp) and cp[ti] == tag_arg.id:
                        add = incoming[encl.name]
            new = add - incoming[callee]
            if new:
                incoming[callee] |= new
                changed = True
    return incoming


def _extract_senders(model, mod, graph, info, classifier, is_role) -> None:
    # wrapper discovery is a whole-tree fixpoint; a module with no
    # direct send/isend call can't define send wrappers (the fixpoint
    # seeds from those calls) and contributes no sender sites — the
    # prefilter keeps the whole-package build inside the <5 s budget
    if not any(
        isinstance(n, ast.Call)
        and astutil.call_last_name(n) in protocol._SEND_NAMES
        for n in mod.nodes
    ):
        return
    wrappers = protocol._send_wrappers(mod.tree)
    payload_info = _wrapper_payload_info(mod, wrappers)
    incoming = _incoming_tags(mod, graph, info, wrappers)
    for node in mod.nodes:
        if not isinstance(node, ast.Call):
            continue
        callee = astutil.call_last_name(node)
        if (
            callee in protocol._SEND_NAMES
            and len(node.args) + len(node.keywords) >= 3
        ):
            tag_arg = astutil.get_arg(node, 1, "tag")
            payload_expr = astutil.get_arg(node, 2, "payload")
        elif callee in wrappers:
            tag_arg = astutil.get_arg(node, wrappers[callee], "tag")
            ppos, _ = payload_info[callee]
            if ppos is None:
                continue  # payload built inside: the inner site covers it
            payload_expr = astutil.get_arg(node, ppos, "payload")
        else:
            continue
        if payload_expr is None:
            continue
        encl = protocol._enclosing_function(node, mod.parents)
        if (
            encl is not None
            and encl.name in wrappers
            and isinstance(payload_expr, ast.Name)
            and payload_info[encl.name][1] == payload_expr.id
        ):
            # the wrapper's own forwarded parameter — classified (with a
            # concrete tag) at each of its call sites instead
            continue
        kinds = classifier.classify(payload_expr, encl)
        site = _site(mod, node)
        text = astutil.line_text(mod.source_lines, node)
        model.payload_sites.append(
            PayloadSite(site=site, kinds=kinds, text=text)
        )
        if not is_role:
            continue
        val, wild = protocol._tag_value(graph, info, tag_arg)
        if val is not None and not wild:
            tags = {val}
        elif (
            isinstance(tag_arg, ast.Name)
            and encl is not None
            and encl.name in wrappers
        ):
            cp = _fn_call_params(encl)
            ti = wrappers[encl.name]
            if ti < len(cp) and cp[ti] == tag_arg.id:
                tags = set(incoming[encl.name])
            else:
                tags = set()
        else:
            tags = set()  # unresolvable tag: skip, never guess
        for t in sorted(tags):
            for k in kinds:
                model.senders.setdefault(t, []).append(
                    SenderShape(tag=t, shape=k, site=site, text=text)
                )


# ---------------------------------------------------------------------------
# receiver extraction


class _RecvExtractor:
    def __init__(self, model, mod, graph, info):
        self.model = model
        self.mod = mod
        self.graph = graph
        self.info = info
        self.local_fns = {
            n.name: n
            for n in mod.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def run(self) -> None:
        mod = self.mod
        wildcard_vars: Set[str] = set()
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_last_name(node)
            if name not in protocol._RECV_NAMES:
                continue
            tag_arg = astutil.get_arg(node, 1, "tag")
            val, wild = protocol._tag_value(self.graph, self.info, tag_arg)
            parent = mod.parents.get(node)
            msgvar = None
            if (
                isinstance(parent, ast.Assign)
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
            ):
                msgvar = parent.targets[0].id
            if wild:
                if msgvar is not None:
                    wildcard_vars.add(msgvar)
            elif val is not None and msgvar is not None:
                # concrete-tag recv: the whole enclosing function is the
                # consumption scope
                encl = protocol._enclosing_function(node, mod.parents)
                scope = encl.body if encl is not None else mod.tree.body
                self._consume(scope, msgvar, set(), val, 0)
        if not wildcard_vars:
            return
        for node in mod.nodes:
            if not isinstance(node, ast.If):
                continue
            tags, msgvar = self._branch_tags(node.test)
            if not tags or msgvar not in wildcard_vars:
                continue
            for t in sorted(tags):
                self._consume(node.body, msgvar, set(), t, 0)

    def _branch_tags(self, test) -> Tuple[Set[int], Optional[str]]:
        comps = []
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
            comps = [
                v for v in test.values if isinstance(v, ast.Compare)
            ]
        elif isinstance(test, ast.Compare):
            comps = [test]
        tags: Set[int] = set()
        msgvar = None
        for c in comps:
            for _cand, dotted in protocol._dispatch_tag_nodes(c):
                val = self.graph.resolve_constant(self.info, dotted)
                if val is not None:
                    tags.add(val)
            for operand in (c.left, *c.comparators):
                if (
                    isinstance(operand, ast.Attribute)
                    and operand.attr == "tag"
                    and isinstance(operand.value, ast.Name)
                ):
                    msgvar = operand.value.id
        return tags, msgvar

    # -- consumption analysis

    def _consume(self, stmts, msgvar, payload_names, tag, depth) -> None:
        rec = self.model.receivers.setdefault(tag, TagRecv())
        mod = self.mod
        roots = set(payload_names)

        def is_root(expr) -> bool:
            if isinstance(expr, ast.Name) and expr.id in roots:
                return True
            return (
                msgvar is not None
                and isinstance(expr, ast.Attribute)
                and expr.attr == "payload"
                and isinstance(expr.value, ast.Name)
                and expr.value.id == msgvar
            )

        nodes = [n for s in stmts for n in ast.walk(s)]
        # alias fixpoint: `payload = msg.payload` (aliases are never
        # killed on rebind — a rebound name's LATER checks, like
        # _admit_push's legacy `len(payload) == 3` after
        # `payload = (epoch, seq, chunk)`, still describe what this
        # branch accepts)
        changed = True
        while changed:
            changed = False
            for n in nodes:
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and is_root(n.value)
                    and n.targets[0].id not in roots
                ):
                    roots.add(n.targets[0].id)
                    changed = True

        consumed: Set[int] = set()

        def consume_expr(expr) -> None:
            consumed.add(id(expr))

        for n in nodes:
            if isinstance(n, (ast.If, ast.While)):
                self._test_patterns(n, rec, is_root, consume_expr)
            elif isinstance(n, ast.Assign):
                if (
                    len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and is_root(n.value)
                ):
                    consume_expr(n.value)  # the alias itself
                elif (
                    len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Tuple)
                    and all(
                        isinstance(e, ast.Name)
                        for e in n.targets[0].elts
                    )
                    and is_root(n.value)
                ):
                    k = len(n.targets[0].elts)
                    rec.arities.setdefault(k, {})
                    rec.arity_sites.setdefault(k, _site(mod, n))
                    consume_expr(n.value)
            elif isinstance(n, ast.Subscript):
                if is_root(n.value) and isinstance(n.ctx, ast.Load):
                    idx = astutil.int_constant(n.slice)
                    if idx is not None and idx >= 0:
                        rec.field_reads.setdefault(idx, _site(mod, n))
                        consume_expr(n.value)
            elif isinstance(n, ast.Compare):
                # `payload is None` / `payload is not None`
                if (
                    len(n.ops) == 1
                    and isinstance(n.ops[0], (ast.Is, ast.IsNot))
                    and isinstance(n.comparators[0], ast.Constant)
                    and n.comparators[0].value is None
                    and is_root(n.left)
                ):
                    rec.none_sites.append(_site(mod, n))
                    consume_expr(n.left)
            elif isinstance(n, ast.Call):
                self._helper_call(
                    n, rec, msgvar, roots, is_root, consume_expr, tag,
                    depth,
                )

        touched = False
        for n in nodes:
            if is_root(n):
                touched = True
                if id(n) in consumed:
                    continue
                if isinstance(n, ast.Name) and not isinstance(
                    n.ctx, ast.Load
                ):
                    continue
                parent = self.mod.parents.get(n)
                if id(parent) in consumed:
                    continue
                rec.any_sites.append(_site(mod, n))
            elif (
                msgvar is not None
                and isinstance(n, ast.Name)
                and n.id == msgvar
                and isinstance(n.ctx, ast.Load)
                and id(n) not in consumed
            ):
                # the message object escaping into an unmodeled call can
                # have its payload consumed any way at all (attribute
                # accesses like msg.tag / msg.src stay transparent)
                parent = self.mod.parents.get(n)
                if isinstance(parent, ast.Call) and n in parent.args:
                    rec.any_sites.append(_site(mod, n))
                    touched = True
        if depth == 0 and not touched:
            # dispatch branch (or recv scope) that never touches the
            # payload — STOP/HEARTBEAT/LEAVE style control messages
            rec.ignored_sites.append(
                _site(mod, stmts[0]) if stmts else Site(mod.rel, 0, 0, "")
            )

    def _test_patterns(self, stmt, rec, is_root, consume_expr) -> None:
        """Arity and isinstance acceptances inside ONE if/while test —
        `len(P) == k` conjoined with `isinstance(P[i], T)` in the same
        test yields an arity-k acceptance with field kinds."""
        test = stmt.test
        len_arities: List[int] = []
        field_types: Dict[int, Set[str]] = {}
        scalar_types: List[Tuple[str, ast.AST]] = []
        tuple_any = None
        for n in ast.walk(test):
            if isinstance(n, ast.Compare) and len(n.ops) == 1:
                left, right = n.left, n.comparators[0]
                if isinstance(n.ops[0], ast.Eq):
                    for a, b in ((left, right), (right, left)):
                        if (
                            isinstance(a, ast.Call)
                            and astutil.call_last_name(a) == "len"
                            and a.args
                            and is_root(a.args[0])
                        ):
                            k = astutil.int_constant(b)
                            if k is not None:
                                len_arities.append(k)
                                consume_expr(a.args[0])
            elif (
                isinstance(n, ast.Call)
                and astutil.call_last_name(n) == "isinstance"
                and len(n.args) == 2
            ):
                subject, types = n.args
                kinds = self._type_kinds(types)
                if is_root(subject):
                    consume_expr(subject)
                    for kind in kinds:
                        if kind == "tuple":
                            tuple_any = n
                        else:
                            scalar_types.append((kind, n))
                elif (
                    isinstance(subject, ast.Subscript)
                    and is_root(subject.value)
                ):
                    idx = astutil.int_constant(subject.slice)
                    if idx is not None and idx >= 0:
                        consume_expr(subject.value)
                        field_types.setdefault(idx, set()).update(
                            k for k in kinds if k != "tuple"
                        )
        mod = self.mod
        if len_arities:
            for k in len_arities:
                fields = rec.arities.setdefault(k, {})
                rec.arity_sites.setdefault(k, _site(mod, stmt))
                for i, kinds in field_types.items():
                    if i < k and kinds:
                        fields.setdefault(i, set()).update(kinds)
        else:
            if tuple_any is not None:
                rec.tuple_any.append(_site(mod, tuple_any))
            for i, kinds in field_types.items():
                rec.field_reads.setdefault(i, _site(mod, stmt))
        for kind, n in scalar_types:
            rec.kinds.setdefault(kind, _site(mod, n))

    @staticmethod
    def _type_kinds(types) -> List[str]:
        cands = (
            types.elts if isinstance(types, ast.Tuple) else [types]
        )
        out = []
        for c in cands:
            dotted = astutil.dotted_name(c)
            if dotted is None:
                continue
            kind = _ISINSTANCE_KINDS.get(dotted.split(".")[-1])
            if kind is not None:
                out.append(kind)
        return out

    def _helper_call(
        self, call, rec, msgvar, roots, is_root, consume_expr, tag, depth
    ) -> None:
        """Follow `self._admit_push(msg)` / `self._parse_join(msg.payload)`
        style module-local helpers: the matching parameter becomes the
        payload root (or message var) inside the helper body."""
        if depth >= MAX_HELPER_DEPTH:
            return
        name = astutil.call_last_name(call)
        fn = self.local_fns.get(name)
        if fn is None:
            return
        params = _fn_call_params(fn)
        new_msgvar = None
        new_payload: Set[str] = set()
        consumed_args = []
        for i, arg in enumerate(call.args):
            if i >= len(params):
                break
            if is_root(arg):
                new_payload.add(params[i])
                consumed_args.append(arg)
            elif (
                msgvar is not None
                and isinstance(arg, ast.Name)
                and arg.id == msgvar
            ):
                new_msgvar = params[i]
                consumed_args.append(arg)
        if not new_payload and new_msgvar is None:
            return
        for arg in consumed_args:
            consume_expr(arg)
        self._consume(fn.body, new_msgvar, new_payload, tag, depth + 1)


# ---------------------------------------------------------------------------
# snapshot schema (save_shard_state writes vs restore reads)


def _snapshot_dict_keys(expr, mod, local_fns, encl, classifier) -> Set[str]:
    """String keys of the dict literal ``expr`` resolves to: a literal,
    a local name assigned one, or a call into a same-module function
    returning one."""

    def keys_of(d: ast.Dict) -> Set[str]:
        return {
            k.value
            for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }

    if isinstance(expr, ast.Dict):
        return keys_of(expr)
    if isinstance(expr, ast.Name) and encl is not None:
        out: Set[str] = set()
        for e in classifier.fn_assigns(encl).get(expr.id, ()):
            if isinstance(e, ast.Dict):
                out |= keys_of(e)
        return out
    if isinstance(expr, ast.Call):
        fn = local_fns.get(astutil.call_last_name(expr))
        if fn is None:
            return set()
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Dict
            ):
                out |= keys_of(node.value)
            elif (
                isinstance(node, ast.Return)
                and isinstance(node.value, ast.Name)
            ):
                for e in classifier.fn_assigns(fn).get(
                    node.value.id, ()
                ):
                    if isinstance(e, ast.Dict):
                        out |= keys_of(e)
        return out
    return set()


def _extract_snapshot(model, mod, classifier) -> None:
    local_fns = {
        n.name: n
        for n in mod.nodes
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in mod.nodes:
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_last_name(node)
        if name == "save_shard_state":
            state = astutil.get_arg(node, 1, "state")
            if state is None:
                continue
            encl = protocol._enclosing_function(node, mod.parents)
            for key in _snapshot_dict_keys(
                state, mod, local_fns, encl, classifier
            ):
                model.snapshot_writes.setdefault(key, _site(mod, node))
        elif name == "load_shard_state":
            parent = mod.parents.get(node)
            if not (
                isinstance(parent, ast.Assign)
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
            ):
                continue
            var = parent.targets[0].id
            encl = protocol._enclosing_function(node, mod.parents)
            scope = encl if encl is not None else mod.tree
            for sub in ast.walk(scope):
                if (
                    isinstance(sub, ast.Subscript)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == var
                    and isinstance(sub.slice, ast.Constant)
                    and isinstance(sub.slice.value, str)
                    and isinstance(sub.ctx, ast.Load)
                ):
                    model.snapshot_reads.setdefault(
                        sub.slice.value, _site(mod, sub)
                    )
                elif (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "get"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == var
                    and sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and isinstance(sub.args[0].value, str)
                ):
                    model.snapshot_reads.setdefault(
                        sub.args[0].value, _site(mod, sub)
                    )


# ---------------------------------------------------------------------------
# driver


def build_schema(project) -> SchemaModel:
    graph = project.graph
    model = SchemaModel()
    class_names = {
        n.name
        for mod in project.modules
        for n in mod.nodes
        if isinstance(n, ast.ClassDef)
    }
    for mod in sorted(project.modules, key=lambda m: m.rel):
        info = graph.module_for_rel(mod.rel)
        if info is not None:
            for cname in sorted(info.constants):
                val = info.constants[cname]
                if (
                    _TAG_NAME_RE.match(cname)
                    and isinstance(val, int)
                    and not isinstance(val, bool)
                ):
                    model.tag_names.setdefault(val, cname)
        classifier = _Classifier(mod, info, class_names)
        # module_role tokenizes the whole source for comments — gate it
        # behind a cheap substring scan (the marker is a literal)
        is_role = any(
            "protocol-role[" in ln for ln in mod.source_lines
        ) and protocol.module_role(mod.source_lines) is not None
        _extract_senders(model, mod, graph, info, classifier, is_role)
        if is_role:
            _RecvExtractor(model, mod, graph, info).run()
        _extract_snapshot(model, mod, classifier)
    return model
