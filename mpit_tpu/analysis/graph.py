"""Whole-program module graph for the static linter (stdlib-only).

The per-file rules (MPT001–MPT006) see one AST at a time; the cross-module
rules (MPT007/MPT008, wrapper-taint MPT004) need to know what a *name* in
one module means in another: which integer ``TAG_PARAM`` resolves to inside
``pclient.py``, whether ``protocol=WIRE_PICKLE_PROTOCOL`` in ``native/``
names the same constant the socket transport pins, and which actual ``def``
sits at the bottom of a ``functools.partial``/alias/decorator-factory chain.

This module builds that index from the parsed trees alone — scanned code is
NEVER imported (the linter must run in bare CI containers without
initializing a jax backend), so resolution is purely syntactic:

- module names derive from scan-root-relative paths
  (``mpit_tpu/parallel/pserver.py`` → ``mpit_tpu.parallel.pserver``,
  ``__init__.py`` collapsing onto its package);
- ``import a.b as c`` / ``from a.b import x as y`` (absolute and relative)
  are followed; ``from a.b import *`` is recorded but deliberately REFUSED
  during resolution — a star import makes every unqualified name in the
  module ambiguous, and a linter that guesses wrong produces false
  positives, so names that could only come from a star import resolve to
  None (the conservative direction: no finding);
- only module-level bindings participate (the registry convention for tags
  and wire constants; function-local state is out of scope);
- callable chains follow plain aliases, ``functools.partial`` (tracking how
  many leading positional parameters the partial consumes and which names
  it binds by keyword), and pure pass-through wrappers
  (``def w(*a, **k): return inner(*a, **k)``), depth-limited so a cycle of
  assignments cannot hang the scan.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import PurePosixPath
from typing import Any, Optional, Union

from mpit_tpu.analysis import astutil

#: resolution depth limit: alias/partial/import chains longer than this are
#: abandoned (also the cycle guard — ``a = b; b = a`` terminates here)
MAX_DEPTH = 16

_CONST_TYPES = (int, float, str, bytes, bool, type(None))


def module_name_for_rel(rel: str) -> str:
    """Dotted module name for a scan-root-relative posix path.

    ``mpit_tpu/parallel/pserver.py`` → ``mpit_tpu.parallel.pserver``;
    a package ``__init__.py`` names the package itself."""
    parts = list(PurePosixPath(rel).parts)
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][: -len(".py")]
    if parts and parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


@dataclasses.dataclass
class ModuleInfo:
    """One module's name-resolution surface (module level only)."""

    rel: str  # scan-root-relative posix path
    name: str  # dotted module name
    tree: ast.Module
    package: str  # enclosing package's dotted name ("" at the top)
    imports: dict  # local name -> absolute dotted target
    star_imports: list  # modules star-imported (resolution refused)
    constants: dict  # name -> literal constant value
    functions: dict  # name -> ast.FunctionDef / ast.AsyncFunctionDef
    assigns: dict  # name -> ast.expr (module-level, non-constant value)


@dataclasses.dataclass(frozen=True)
class Resolved:
    """One resolution step's answer: what ``dotted`` names in ``module``."""

    kind: str  # "constant" | "function" | "assign" | "module"
    value: Any  # const value | FunctionDef | expr | None (module)
    module: Optional[ModuleInfo]  # defining module (None: const folded)


@dataclasses.dataclass(frozen=True)
class CallableInfo:
    """A callable chain resolved down to its underlying ``def``.

    ``bound_pos`` leading positional parameters (and ``bound_names``
    keyword-bound parameters) have been consumed by ``functools.partial``
    links along the chain; ``depth`` counts the links followed."""

    fn: Union[ast.FunctionDef, ast.AsyncFunctionDef]
    module: ModuleInfo
    bound_pos: int = 0
    bound_names: frozenset = frozenset()
    depth: int = 0


def _resolve_relative_base(info_name: str, is_package: bool, level: int) -> str:
    """The absolute package a ``from ...x import y`` resolves against."""
    parts = info_name.split(".") if info_name else []
    if not is_package:
        parts = parts[:-1]  # a plain module's level-1 base is its package
    drop = level - 1
    if drop:
        parts = parts[: -drop] if drop <= len(parts) else []
    return ".".join(parts)


def build_module_info(rel: str, tree: ast.Module) -> ModuleInfo:
    name = module_name_for_rel(rel)
    is_package = PurePosixPath(rel).name == "__init__.py"
    package = name if is_package else ".".join(name.split(".")[:-1])
    imports: dict = {}
    star_imports: list = []
    constants: dict = {}
    functions: dict = {}
    assigns: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    # ``import a.b.c`` binds only ``a`` — dotted uses are
                    # resolved as absolute paths by the graph lookup
                    head = alias.name.split(".")[0]
                    imports.setdefault(head, head)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = _resolve_relative_base(name, is_package, node.level)
                mod = f"{base}.{node.module}" if node.module else base
                mod = mod.lstrip(".")
            else:
                mod = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    star_imports.append(mod)
                else:
                    imports[alias.asname or alias.name] = (
                        f"{mod}.{alias.name}" if mod else alias.name
                    )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                _record_binding(tgt.id, node.value, constants, assigns)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                _record_binding(
                    node.target.id, node.value, constants, assigns
                )
    return ModuleInfo(
        rel=rel,
        name=name,
        tree=tree,
        package=package,
        imports=imports,
        star_imports=star_imports,
        constants=constants,
        functions=functions,
        assigns=assigns,
    )


def _record_binding(name: str, value: ast.expr, constants, assigns) -> None:
    if isinstance(value, ast.Constant) and isinstance(
        value.value, _CONST_TYPES
    ):
        constants[name] = value.value
        return
    folded = astutil.int_constant(value)  # -1 and friends
    if folded is not None:
        constants[name] = folded
        return
    assigns[name] = value


class ModuleGraph:
    """Cross-module name resolution over a scan set.

    Built once per lint run from the already-parsed ``ModuleCtx`` list
    (anything with ``.rel`` and ``.tree``); rules reach it through
    ``project.graph``."""

    def __init__(self, modules) -> None:
        self.by_name: dict = {}
        self.by_rel: dict = {}
        for m in modules:
            info = build_module_info(m.rel, m.tree)
            self.by_name[info.name] = info
            self.by_rel[info.rel] = info

    # -- lookup ----------------------------------------------------------

    def module(self, name: str) -> Optional[ModuleInfo]:
        return self.by_name.get(name)

    def module_for_rel(self, rel: str) -> Optional[ModuleInfo]:
        return self.by_rel.get(rel)

    # -- core resolution -------------------------------------------------

    def resolve(
        self, info: Optional[ModuleInfo], dotted: str, depth: int = 0
    ) -> Optional[Resolved]:
        """What ``dotted`` names when written inside ``info``.

        Follows import aliases across the scan set; returns None for
        anything outside it (stdlib, jax, ...), for class attributes, and
        for names reachable only through a ``from x import *`` (refused —
        see the module docstring)."""
        if depth > MAX_DEPTH or not dotted:
            return None
        parts = dotted.split(".")
        head = parts[0]
        if info is not None:
            if len(parts) == 1:
                hit = self._local(info, head)
                if hit is not None:
                    return hit
            if head in info.imports:
                target = info.imports[head]
                rest = ".".join(parts[1:])
                full = f"{target}.{rest}" if rest else target
                return self._resolve_absolute(full, depth + 1)
        if len(parts) > 1:
            return self._resolve_absolute(dotted, depth + 1)
        return None

    def _local(self, info: ModuleInfo, name: str) -> Optional[Resolved]:
        if name in info.constants:
            return Resolved("constant", info.constants[name], info)
        if name in info.functions:
            return Resolved("function", info.functions[name], info)
        if name in info.assigns:
            return Resolved("assign", info.assigns[name], info)
        return None

    def _resolve_absolute(
        self, dotted: str, depth: int
    ) -> Optional[Resolved]:
        if depth > MAX_DEPTH:
            return None
        parts = dotted.split(".")
        # longest module prefix wins (a name can shadow a subpackage only
        # through __init__ re-exports, which the imports table handles)
        for cut in range(len(parts), 0, -1):
            mod = self.by_name.get(".".join(parts[:cut]))
            if mod is None:
                continue
            rest = parts[cut:]
            if not rest:
                return Resolved("module", None, mod)
            if len(rest) > 1:
                return None  # Class.attr etc. — out of scope
            name = rest[0]
            hit = self._local(mod, name)
            if hit is not None:
                return hit
            if name in mod.imports:
                return self._resolve_absolute(mod.imports[name], depth + 1)
            # name not found; a star import COULD provide it — refuse
            # rather than guess (documented star-import rejection)
            return None
        return None

    # -- constants -------------------------------------------------------

    def resolve_constant(
        self,
        info: Optional[ModuleInfo],
        node_or_dotted,
        depth: int = 0,
    ) -> Optional[Any]:
        """Literal value of an expression/name, following alias chains.

        Accepts an AST node (Constant / Name / Attribute) or a dotted
        string. Returns None when the chain leaves the scan set, hits a
        star import, or ends on anything but a literal."""
        if depth > MAX_DEPTH:
            return None
        if isinstance(node_or_dotted, ast.AST):
            node = node_or_dotted
            folded = astutil.int_constant(node)
            if folded is not None:
                return folded
            if isinstance(node, ast.Constant) and isinstance(
                node.value, _CONST_TYPES
            ):
                return node.value
            if isinstance(node, ast.BinOp):
                # fold arithmetic/concatenation whose operands resolve —
                # ``TAG_BASE + 1`` is a real registry idiom, and skipping
                # it silently exempted such tags from MPT002/MPT008
                return astutil.fold_binop(
                    node.op,
                    self.resolve_constant(info, node.left, depth + 1),
                    self.resolve_constant(info, node.right, depth + 1),
                )
            if isinstance(node, ast.UnaryOp):
                return astutil.fold_unaryop(
                    node.op,
                    self.resolve_constant(info, node.operand, depth + 1),
                )
            dotted = astutil.dotted_name(node)
            if dotted is None:
                return None
        else:
            dotted = node_or_dotted
        r = self.resolve(info, dotted, depth)
        if r is None:
            return None
        if r.kind == "constant":
            return r.value
        if r.kind == "assign":
            return self.resolve_constant(r.module, r.value, depth + 1)
        return None

    # -- callables -------------------------------------------------------

    def resolve_callable(
        self,
        info: Optional[ModuleInfo],
        node_or_dotted,
        depth: int = 0,
    ) -> Optional[CallableInfo]:
        """Follow a wrapper chain down to its defining ``def``.

        Links followed: name/attribute aliases (within and across
        modules), ``functools.partial(inner, ...)`` (accumulating bound
        leading positionals and keyword-bound names), and pure
        pass-through wrappers (``def w(*a, **k): return inner(*a, **k)``).
        Returns None when the chain can't be tracked — unknown call
        shapes, star imports, lambdas, or anything off the scan set."""
        if depth > MAX_DEPTH:
            return None
        node = node_or_dotted
        if isinstance(node, str) or isinstance(
            node, (ast.Name, ast.Attribute)
        ):
            dotted = (
                node if isinstance(node, str) else astutil.dotted_name(node)
            )
            if dotted is None:
                return None
            r = self.resolve(info, dotted, depth)
            if r is None:
                return None
            if r.kind == "function":
                return self._unwrap_passthrough(
                    CallableInfo(r.value, r.module, 0, frozenset(), depth),
                    depth,
                )
            if r.kind == "assign":
                return self.resolve_callable(r.module, r.value, depth + 1)
            return None
        if isinstance(node, ast.Call):
            fn_dotted = astutil.dotted_name(node.func)
            if (
                fn_dotted is not None
                and fn_dotted.split(".")[-1] == "partial"
                and node.args
            ):
                inner = self.resolve_callable(info, node.args[0], depth + 1)
                if inner is None:
                    return None
                return CallableInfo(
                    fn=inner.fn,
                    module=inner.module,
                    bound_pos=inner.bound_pos + len(node.args) - 1,
                    bound_names=inner.bound_names
                    | {k.arg for k in node.keywords if k.arg},
                    depth=inner.depth + 1,
                )
            return None
        return None

    def _unwrap_passthrough(
        self, ci: CallableInfo, depth: int
    ) -> Optional[CallableInfo]:
        """``def w(*a, **k): return inner(*a, **k)`` contributes nothing to
        the signature — resolve through it to ``inner``."""
        fn = ci.fn
        a = fn.args
        if (
            a.posonlyargs
            or a.args
            or a.kwonlyargs
            or a.vararg is None
            or len(fn.body) != 1
            or not isinstance(fn.body[0], ast.Return)
            or not isinstance(fn.body[0].value, ast.Call)
        ):
            return ci
        call = fn.body[0].value
        if not (
            len(call.args) == 1
            and isinstance(call.args[0], ast.Starred)
            and isinstance(call.args[0].value, ast.Name)
            and call.args[0].value.id == a.vararg.arg
        ):
            return ci
        inner = self.resolve_callable(ci.module, call.func, depth + 1)
        if inner is None:
            return ci  # can't see through: report against the wrapper
        return CallableInfo(
            fn=inner.fn,
            module=inner.module,
            bound_pos=ci.bound_pos + inner.bound_pos,
            bound_names=ci.bound_names | inner.bound_names,
            depth=inner.depth + 1,
        )
