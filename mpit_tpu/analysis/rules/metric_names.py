"""MPT012 — live metric names must come from the registered namespace.

The live telemetry plane (:mod:`mpit_tpu.obs.live`) keys every series by
a string: ``reg.inc("train.samples")`` and ``reg.inc("train.sample")``
are both perfectly legal Python and produce two silently diverging
series — the dashboard, the straggler alert, and the SLO burn rate all
read specific keys, so a typo'd publish doesn't fail, it just makes a
metric flatline. The namespace is therefore a registry: the module-level
``M_*`` string constants in ``mpit_tpu/obs/live.py``, and every publish
(``inc`` / ``set_gauge`` / ``observe`` first argument) must name one of
them *by constant*.

Checked only in modules that import the live plane (``mpit_tpu.obs.live``
or one of its hooks) — ``observe`` is a common method name
(``LogicalClock.observe``, ``SLOAggregator.observe``) and modules outside
the live plane's import closure can't be publishing into a registry.
Within scope:

- a string literal first argument is always flagged, even when its value
  matches a registered name (the MPT007 idiom: a later rename of the
  constant would silently strand the literal);
- a name/attribute that resolves (through the import graph's alias
  chains) to a string not among the registered values is flagged;
- an unresolvable name spelled like a namespace constant (``M_FOO``)
  that is NOT defined in the namespace is flagged — that is exactly what
  a typo'd import or a deleted constant looks like;
- anything else unresolvable (locals, computed names) is out of static
  scope, same stance as MPT007 on dynamic protocol expressions.

The canonical namespace is AST-parsed from the scan set when it covers
``obs/live.py``, else from the installed package next to this rule —
never imported (the linter must stay side-effect free).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

RULES = {
    "MPT012": (
        "unregistered-metric-name",
        "registry publish (inc/set_gauge/observe) whose metric name is a "
        "string literal or does not resolve to an M_* constant in "
        "mpit_tpu.obs.live — typo'd keys fork or flatline a series "
        "silently",
    ),
}

_PUBLISH_METHODS = frozenset({"inc", "set_gauge", "observe"})
_NAMESPACE_REL_SUFFIX = "obs/live.py"
_LIVE_MODULE = "mpit_tpu.obs.live"
_LIVE_HOOKS = frozenset({"live_registry", "NULL_REGISTRY", "MetricsRegistry"})
_M_NAME_RE = re.compile(r"^M_[A-Z0-9_]+$")


def _module_metric_names(tree: ast.Module) -> dict:
    """Module-level ``M_* = "literal"`` assigns — the namespace shape."""
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and _M_NAME_RE.match(tgt.id)):
            continue
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, str
        ):
            out[tgt.id] = node.value.value
    return out


def canonical_namespace(project) -> Optional[tuple]:
    """({constant name: value}, where) for the registered metric
    namespace, or None when it can't be located (then nothing is
    checked — there is no registry to drift from)."""
    for mod in project.modules:
        if mod.rel.endswith(_NAMESPACE_REL_SUFFIX):
            names = _module_metric_names(mod.tree)
            if names:
                return names, mod.rel
    # scan set doesn't cover the live module: fall back to the installed
    # package relative to this file (parsed, never imported)
    canon = Path(__file__).resolve().parents[2] / "obs" / "live.py"
    try:
        tree = ast.parse(canon.read_text())
    except (OSError, SyntaxError):
        return None
    names = _module_metric_names(tree)
    if names:
        return names, "mpit_tpu/" + _NAMESPACE_REL_SUFFIX
    return None


def _imports_live(tree: ast.Module) -> bool:
    """Does this module pull in the live plane? Import of the module (any
    spelling) or of one of its hook names from the obs package."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == _LIVE_MODULE for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m == _LIVE_MODULE:
                return True
            if m.endswith("obs") and any(
                a.name == "live" or a.name in _LIVE_HOOKS
                for a in node.names
            ):
                return True
    return False


def _check_publish(mod, info, graph, call, dotted_fn, names, where):
    values = set(names.values())
    arg = astutil.get_arg(call, 0, "name")
    if arg is None:
        return
    if isinstance(arg, ast.Constant):
        if not isinstance(arg.value, str):
            return  # some other .observe()/.inc() API — not a metric name
        verdict = (
            "is not a registered metric name"
            if arg.value not in values
            else "matches a registered name by value, but a rename of "
            "the constant would silently strand it"
        )
        yield mod.finding(
            "MPT012",
            call,
            f"{dotted_fn}({arg.value!r}, ...) publishes a literal metric "
            f"name — {verdict}; use the M_* constant from "
            f"{_LIVE_MODULE} ({where})",
        )
        return
    dotted = astutil.dotted_name(arg)
    if dotted is None:
        return  # computed name: out of static scope
    last = dotted.split(".")[-1]
    resolved = graph.resolve_constant(info, arg)
    if isinstance(resolved, str):
        if resolved not in values:
            yield mod.finding(
                "MPT012",
                call,
                f"{dotted_fn}({dotted}, ...): {dotted} resolves to "
                f"{resolved!r}, which is not a registered metric name "
                f"in {_LIVE_MODULE} ({where}) — this series is "
                "invisible to the dashboard and alerts",
            )
    elif resolved is None:
        # unresolvable: accept only spellings the namespace defines
        # (covers linting a single file whose imports are off the scan
        # set); a namespace-shaped name the registry lacks is a typo
        if _M_NAME_RE.match(last) and last not in names:
            yield mod.finding(
                "MPT012",
                call,
                f"{dotted_fn}({dotted}, ...) names {last}, which is not "
                f"defined in the metric namespace ({where}) — typo or "
                "deleted constant",
            )
    # non-string resolution (int, tuple): a different API, not a metric


def run(project) -> Iterable:
    canon = canonical_namespace(project)
    if canon is None:
        return
    names, where = canon
    graph = project.graph
    for mod in project.modules:
        if mod.rel.endswith(_NAMESPACE_REL_SUFFIX):
            continue  # the registry itself (its helpers take computed names)
        if not _imports_live(mod.tree):
            continue
        info = graph.module_for_rel(mod.rel)
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue  # bare inc()/observe() is some other function
            if node.func.attr not in _PUBLISH_METHODS:
                continue
            dotted_fn = astutil.dotted_name(node.func) or node.func.attr
            yield from _check_publish(
                mod, info, graph, node, dotted_fn, names, where
            )
