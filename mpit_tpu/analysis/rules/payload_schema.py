"""MPT016-018: payload-schema rules over the wire-schema model
(:mod:`mpit_tpu.analysis.schema`, ``project.schema``).

MPT016 compares what each tag's senders construct against what its
receivers destructure. A receiver with an *opaque* consumption path
(``np.asarray(msg.payload)`` fallthrough, the message escaping into
unmodeled code) accepts everything — only a fully-constrained receiver
can falsify a sender shape, so "no finding" stays the conservative
default. The receiver-side half flags a constant-index read beyond every
sender's arity: a field the reader expects that no writer ever packs.

MPT017 classifies EVERY transport send payload (role-marked or not):
any construction containing a dict/set/comprehension/custom-object kind
falls off ``encode_frame`` onto the per-message pickle fallback — a 2x
serialize regression on a hot-path envelope, and a silent one.

MPT018 diffs the snapshot schema: string keys written through
``save_shard_state`` vs keys the ``load_shard_state`` consumer reads.
A read with no writer is the restore-time KeyError/default-drift bug
class; a write nothing reads is dead freight that masks it.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional

from mpit_tpu.analysis import schema as schema_mod
from mpit_tpu.analysis.findings import Finding

RULES = {
    "MPT016": (
        "sender/receiver payload-shape divergence",
        "a tag's sender constructs a payload shape its (fully "
        "constrained) receiver never destructures — the message is "
        "dropped or mis-unpacked at dispatch",
    ),
    "MPT017": (
        "payload rides the pickle fallback",
        "a send constructs a dict/set/custom object that falls off the "
        "structural wire codec onto per-message pickle — 2x serialize "
        "cost and no schema, silently",
    ),
    "MPT018": (
        "snapshot schema drift",
        "fields written by save_shard_state and fields restore reads "
        "have diverged — restore sees defaults (or nothing) where the "
        "snapshot meant data",
    ),
}


def _emit(by_rel, rule, site, message) -> Optional[Finding]:
    mod = by_rel.get(site.rel)
    if mod is None:
        return None
    anchor = ast.Pass()
    anchor.lineno = site.line
    anchor.col_offset = site.col
    f = mod.finding(rule, anchor, message)
    return dataclasses.replace(f, symbol=site.symbol)


def _kinds_match(sender_kind, recv_kind) -> bool:
    if sender_kind == recv_kind:
        return True
    if sender_kind == "bool" and recv_kind == "int":
        return True  # bools are ints everywhere the protocol cares
    if schema_mod.is_tuple_kind(sender_kind) and recv_kind == "tuple":
        return True
    return False


def _field_overlap(sender_kinds, recv_kinds) -> bool:
    return any(
        _kinds_match(s, r) for s in sender_kinds for r in recv_kinds
    )


def _shape_compatible(shape, rec) -> bool:
    if shape == "unknown":
        return True
    if shape == "none":
        return bool(rec.none_sites)
    if schema_mod.is_tuple_kind(shape):
        k = len(shape[1])
        if rec.tuple_any:
            return True
        if not rec.arities:
            # the receiver only subscripts the payload (no len/unpack
            # check): any tuple covering every read index is fine
            if rec.field_reads:
                return all(i < k for i in rec.field_reads)
            return False  # receiver accepts only scalars/None
        if k not in rec.arities:
            return False
        fields = rec.arities[k]
        for i, sender_kinds in enumerate(shape[1]):
            recv_kinds = fields.get(i)
            if not recv_kinds:
                continue  # receiver doesn't constrain this field
            if not sender_kinds or "unknown" in sender_kinds:
                continue  # sender side unresolved: no claim
            if not _field_overlap(sender_kinds, recv_kinds):
                return False
        return True
    # scalar/array kinds need an isinstance acceptance on the receiver
    return _field_overlap({shape}, set(rec.kinds))


def _mpt016(model, by_rel) -> Iterable[Finding]:
    for tag in sorted(model.senders):
        rec = model.receivers.get(tag)
        if rec is None or rec.opaque or not rec.constrained:
            continue
        accepted = schema_mod.receiver_repr(rec)
        for s in model.senders[tag]:
            if _shape_compatible(s.shape, rec):
                continue
            f = _emit(
                by_rel,
                "MPT016",
                s.site,
                f"{model.tag_name(tag)} sender payload "
                f"{schema_mod.kind_repr(s.shape)} matches none of the "
                f"receiver's accepted shapes {accepted} — the receiver "
                "mis-unpacks or drops this message",
            )
            if f is not None:
                yield f
    for tag in sorted(model.receivers):
        senders = model.senders.get(tag)
        if not senders:
            continue
        shapes = [s.shape for s in senders]
        if not all(schema_mod.is_tuple_kind(sh) for sh in shapes):
            continue  # a non-tuple/unknown sender could carry anything
        max_arity = max(len(sh[1]) for sh in shapes)
        rec = model.receivers[tag]
        for i in sorted(rec.field_reads):
            if i < max_arity:
                continue
            f = _emit(
                by_rel,
                "MPT016",
                rec.field_reads[i],
                f"{model.tag_name(tag)} receiver reads payload field "
                f"[{i}] but every sender packs at most {max_arity} "
                "fields — this index can never exist",
            )
            if f is not None:
                yield f


def _offending_kinds(kinds) -> List[str]:
    out: List[str] = []
    for k in kinds:
        if isinstance(k, str) and k.startswith("unencodable:"):
            out.append(k.split(":", 1)[1])
        elif schema_mod.is_tuple_kind(k):
            for fs in k[1]:
                out.extend(_offending_kinds(fs))
    return out


def _mpt017(model, by_rel) -> Iterable[Finding]:
    for ps in model.payload_sites:
        offenders = sorted(set(_offending_kinds(ps.kinds)))
        if not offenders:
            continue
        f = _emit(
            by_rel,
            "MPT017",
            ps.site,
            "send payload contains "
            + ", ".join(offenders)
            + " — unencodable by the structural wire codec, so the "
            "whole message rides the per-message pickle fallback",
        )
        if f is not None:
            yield f


def _mpt018(model, by_rel) -> Iterable[Finding]:
    writes, reads = model.snapshot_writes, model.snapshot_reads
    if not writes or not reads:
        return  # only diff when both halves are statically visible
    for key in sorted(set(reads) - set(writes)):
        f = _emit(
            by_rel,
            "MPT018",
            reads[key],
            f"restore reads snapshot field {key!r} that no "
            "save_shard_state writer ever packs — it always lands on "
            "the default (or KeyErrors)",
        )
        if f is not None:
            yield f
    for key in sorted(set(writes) - set(reads)):
        f = _emit(
            by_rel,
            "MPT018",
            writes[key],
            f"snapshot writes field {key!r} that restore never reads — "
            "dead freight that hides real schema drift",
        )
        if f is not None:
            yield f


def run(project) -> Iterable[Finding]:
    model = project.schema
    by_rel = {m.rel: m for m in project.modules}
    out: List[Finding] = []
    out.extend(_mpt016(model, by_rel))
    out.extend(_mpt017(model, by_rel))
    out.extend(_mpt018(model, by_rel))
    return out
