"""MPT009/MPT010/MPT011 — model-checked protocol safety properties.

Where MPT008 pairs tag alphabets, these rules run the explicit-state
model checker (:mod:`mpit_tpu.analysis.mcheck`) over the fault-handling
semantics that :func:`mpit_tpu.analysis.protocol.extract_semantics`
lifts out of the marked role modules — the attempt-id echo/check, the
reply-wait timeout, and the dedup window's exact admit boundary — and
exhaustively explore every single-fault message interleaving of the
lint-tier configurations (1 client x 1 server, EASGD and Downpour step
orders, window 1, bounded rounds; the hazards are per-client-per-server,
and tests/test_mcheck.py runs the 2-client acceptance pair):

- **MPT009** exactly-once push application: some reachable fault
  schedule makes one server apply the same ``(client, seq)`` push twice
  (classically: the dedup boundary uses ``<`` where ``<=`` is needed, so
  a duplicated copy delivered after the window slid is re-admitted);
- **MPT010** deadlock freedom: some reachable state has no enabled
  transition yet the run isn't finished (a blocking recv with no escape
  — e.g. a dropped request and no timeout on the reply wait);
- **MPT011** stale-attempt isolation: a reply generated for a timed-out
  attempt is assembled into a newer fetch (no attempt id on the wire, or
  an echoed id the client never compares).

Conservatism: roles without fault machinery (no attempt echo AND no
dedup window — e.g. the tiny lint fixtures) are protocol sketches, not
fault-tolerant PS implementations, and are skipped entirely; a dedup
admit whose shape the extractor can't parse (``dedup_opaque``) is
assumed correct rather than guessed at. Whatever the checker reports is
a real trace of the extracted model, and the finding message carries the
violating configuration plus the explored state count as the
exhaustiveness receipt.

Results are memoized on the extracted semantics (frozen dataclasses), so
repeated ``run_lint`` calls in one process — the test suite, ``--fix``
re-checks — pay for the exploration once.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from mpit_tpu.analysis import mcheck, protocol

RULES = {
    "MPT009": (
        "push-applied-twice",
        "a fault schedule exists where the dedup window admits the same "
        "(client, seq) push twice — exactly-once application is violated",
    ),
    "MPT010": (
        "protocol-deadlock",
        "a fault schedule reaches a state where every role is blocked "
        "and no message can unblock them",
    ),
    "MPT011": (
        "stale-reply-assembled",
        "a reply from a timed-out attempt can be assembled into a newer "
        "fetch — attempt ids are missing or never checked",
    ),
}

# extracted-semantics -> list[CheckResult]; ProtocolSemantics is frozen
# and hashable, so identical protocols (every run_lint over this repo)
# share one exploration per process
_CACHE: dict = {}


def _anchor(rel: str, line: int, col: int) -> ast.AST:
    node = ast.Constant(0)
    node.lineno, node.col_offset = line, col
    return node


def _emit(by_rel, rel, line, col, symbol, rule, message):
    mod = by_rel.get(rel)
    if mod is not None:
        f = mod.finding(rule, _anchor(rel, line, col), message)
        # the synthetic anchor has no parents entry; the extraction
        # already carries the real enclosing symbol
        yield dataclasses.replace(f, symbol=symbol)


def _site(sem: protocol.ProtocolSemantics, rule: str):
    """(rel, line, col, symbol) to pin each property's finding to: the
    dedup admit for exactly-once, the client's reply recv for deadlock
    and (when an echo exists but isn't compared) staleness, the server's
    reply send when no attempt id is on the wire at all."""
    if rule == "MPT009" and sem.dedup is not None:
        d = sem.dedup
        return d.rel, d.line, d.col, d.symbol
    if rule == "MPT011" and not sem.attempt_echoed:
        op = sem.reply_send
    else:
        op = sem.reply_recv
    return op.rel, op.line, op.col, op.symbol


def results_for(sem: protocol.ProtocolSemantics) -> list:
    if sem not in _CACHE:
        # quick: the default and sharded configs run their 1-client
        # lint-tier variants (hundreds of states each) — the 2-client
        # exhaustive runs are test_mcheck.py's acceptance job, not the
        # pre-commit scan's
        _CACHE[sem] = mcheck.check_all(
            mcheck.from_protocol(sem), quick=True
        )
    return _CACHE[sem]


def run(project) -> Iterable:
    sem: Optional[protocol.ProtocolSemantics] = protocol.extract_semantics(
        project
    )
    if sem is None or not sem.has_fault_machinery:
        return
    by_rel = {m.rel: m for m in project.modules}
    reported = set()
    for res in results_for(sem):
        for rule in sorted(res.violations):
            if rule in reported:
                continue  # first violating configuration wins
            reported.add(rule)
            rel, line, col, symbol = _site(sem, rule)
            yield from _emit(
                by_rel,
                rel,
                line,
                col,
                symbol,
                rule,
                res.violations[rule]
                + f" (exhaustive: {res.states} states, "
                f"{res.fault_points} single-fault schedules)",
            )
        if res.truncated:
            rel, line, col, symbol = _site(sem, "MPT010")
            yield from _emit(
                by_rel,
                rel,
                line,
                col,
                symbol,
                "MPT010",
                f"[{res.config.label}] state space exceeded "
                f"{res.config.max_states} states — exploration truncated, "
                "deadlock freedom NOT established",
            )
            break
