"""MPT013-015: whole-program race, lock-order and blocking-under-lock
rules, all consumers of the concurrency model in
:mod:`mpit_tpu.analysis.threads` (``project.threads``).

MPT013 is an Eraser-style lockset check: state written from one thread
root and touched from another, where some cross-root access pair shares
NO lock, has no consistent protection discipline — the access can
interleave. Init-phase accesses (``__init__`` bodies, closure setup
before the first ``Thread()`` spawn) and constant stop-flag stores are
exempt, matching the classic algorithm's initialization state.

MPT014 is the static twin of runtime RT101: a cycle in the held→acquired
lock graph across ALL call paths and thread roots means two threads can
enter the cycle from different edges and deadlock, even if no single
test run (which is all RT101 sees) ever interleaves them.

MPT015 escalates MPT006 to call-graph depth: a blocking call is flagged
when a lock acquired in an ANCESTOR frame is still held — the shape
MPT006 structurally cannot see, and the one that actually bites (the
leaf function looks innocent in isolation). Same-frame cases remain
MPT006's jurisdiction, so the two rules never double-report.
"""

from __future__ import annotations

from mpit_tpu.analysis.findings import Finding

RULES = {
    "MPT013": (
        "unlocked cross-thread shared state",
        "state written from >=2 thread roots with an empty/inconsistent "
        "lockset can interleave — protect it or confine it to one thread",
    ),
    "MPT014": (
        "static lock-order cycle",
        "two call paths acquire the same locks in opposite orders — "
        "threads entering from different edges deadlock",
    ),
    "MPT015": (
        "blocking call under a caller's lock",
        "an indefinitely-blocking call runs while a lock acquired in an "
        "ancestor frame is held — stalls every thread contending for it",
    ),
}


def _fmt_lockset(ls) -> str:
    if not ls:
        return "{}"
    return "{" + ", ".join(sorted(l.short() for l in ls)) + "}"


def _mpt013(model):
    for state, per_root in sorted(
        model.shared_state().items(), key=lambda kv: kv[0].label()
    ):
        writes = {r: e for r, e in per_root.items() if e["writes"]}
        if not writes:
            continue
        if all(e["all_const_writes"] for e in writes.values()):
            continue  # pure flag stores: GIL-atomic by design
        # find a cross-root pair with an empty lockset intersection —
        # preferring an UNLOCKED write as the anchor (the actionable side)
        def _ls_key(ls):
            return (len(ls), sorted(l.label() for l in ls))

        def _w_order(item):
            root, entry = item
            return (min(len(ls) for ls in entry["write_locksets"]), root)

        offender = None
        for wroot, wentry in sorted(writes.items(), key=_w_order):
            for oroot, oentry in sorted(per_root.items()):
                if oroot == wroot:
                    continue
                for wls in sorted(wentry["write_locksets"], key=_ls_key):
                    for ols in sorted(oentry["locksets"], key=_ls_key):
                        if not (wls & ols):
                            offender = (wroot, wls, oroot, ols, wentry)
                            break
                    if offender:
                        break
                if offender:
                    break
            if offender:
                break
        if offender is None:
            continue
        wroot, wls, oroot, ols, wentry = offender
        anchor = wentry["write_example"] or wentry["example"]
        yield anchor, (
            f"{state.label()} is written from thread root "
            f"'{wroot}' holding {_fmt_lockset(wls)} and accessed from "
            f"'{oroot}' holding {_fmt_lockset(ols)} — no common lock; "
            "guard both sides with one lock or confine the state to a "
            "single thread"
        )


def _mpt014(model):
    for path, edges in model.lock_cycles():
        names = " -> ".join(l.short() for l in path + [path[0]])
        anchor = edges[0]
        others = "; ".join(
            f"{e.held.short()}->{e.acquired.short()} at "
            f"{e.mod.rel}:{e.node.lineno} ({e.symbol}, root '{e.root}')"
            for e in edges
        )
        yield anchor, (
            f"lock-order cycle {names}: {others} — fix by imposing one "
            "global acquisition order (see RT101 for the runtime twin)"
        )


def _mpt015(model):
    seen = set()
    for site in model.blocking:
        lock = sorted(site.cross_locks, key=lambda l: l.label())[0]
        key = (site.mod.rel, site.node.lineno, site.call, lock)
        if key in seen:
            continue
        seen.add(key)
        yield site, (
            f"blocking call '{site.call}()' runs while holding "
            f"{_fmt_lockset(site.cross_locks)} acquired in a CALLER frame "
            f"(thread root '{site.root}') — the critical section spans "
            "this whole call chain; move the blocking call outside it"
        )


def run(project):
    model = project.threads
    for anchor, message in _mpt013(model):
        yield _finding(project, "MPT013", anchor.mod, anchor.node, message)
    for anchor, message in _mpt014(model):
        yield _finding(project, "MPT014", anchor.mod, anchor.node, message)
    for site, message in _mpt015(model):
        yield _finding(project, "MPT015", site.mod, site.node, message)


def _finding(project, rule, mod, node, message) -> Finding:
    return mod.finding(rule, node, message)
