"""MPT005 — host-device synchronization inside a hot-path loop.

A ``.item()`` / ``float(loss)`` / ``np.asarray(x)`` / ``block_until_ready``
in a step loop stalls the XLA dispatch pipeline every iteration — and over
a remote device tunnel it times the round-trip rather than the training
(the measured failure documented at ``parallel/ps_roles.client_train_loop``:
batch the fetch at the τ boundary instead). Flagged only in the hot-path
modules (``run.py``, ``parallel/``, ``ops/``) and only syntactically inside
a loop body.

Sanctioned syncs: calls to barrier functions (``force_completion`` — the
documented proof-of-completion barrier in ``utils/profiling.py`` — plus any
def carrying the ``# mpit-analysis: host-sync-barrier`` marker), code inside
such a barrier's own body, and lines carrying an inline
``# mpit-analysis: ignore[MPT005]``. Accepted per-iteration syncs (e.g. the
τ-boundary flatten in ``ps_roles``) live in the baseline.
"""

from __future__ import annotations

import ast
from typing import Iterable

from mpit_tpu.analysis import astutil

RULES = {
    "MPT005": (
        "host-sync-in-loop",
        ".item()/float()/np.asarray()/block_until_ready inside a loop in "
        "a hot-path module stalls the dispatch pipeline every iteration",
    ),
}

_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_DOTTED_LAST = {"block_until_ready", "device_get"}
# only NUMPY asarray/array force a device->host transfer; jnp.asarray is a
# device-side cast and stays out of scope
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_CAST_BUILTINS = {"float", "int"}


def _numpy_names(tree: ast.Module) -> set:
    names = set(_NUMPY_ALIASES)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    names.add(alias.asname or "numpy")
    return names


def _sync_reason(node: ast.Call, np_names: set) -> str:
    """Why this call is a host sync, or '' if it isn't one."""
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
        return f".{func.attr}() forces a device->host transfer"
    dotted = astutil.dotted_name(func)
    if dotted is not None:
        parts = dotted.split(".")
        if parts[-1] in _SYNC_DOTTED_LAST:
            return f"{dotted}() blocks on device completion"
        if (
            parts[-1] in ("asarray", "array")
            and len(parts) > 1
            and parts[0] in np_names
        ):
            return (
                f"{dotted}() materializes a device array on the host"
            )
        if (
            len(parts) == 1
            and parts[0] in _CAST_BUILTINS
            and len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)
        ):
            return (
                f"{parts[0]}() on a device scalar blocks until the "
                "value is computed and fetched"
            )
    return ""


def _inside_barrier_call(node: ast.AST, parents: dict, barriers: set):
    """Is ``node`` an argument of a sanctioned barrier call?"""
    cur = parents.get(node)
    while cur is not None and not isinstance(
        cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.stmt)
    ):
        if isinstance(cur, ast.Call):
            name = astutil.call_last_name(cur)
            if name in barriers:
                return True
        cur = parents.get(cur)
    return False


def run(project) -> Iterable:
    # barrier names: config defaults + every marker-annotated def anywhere
    # in the scan set (the marker travels with the function, not the config)
    barriers = set(project.config.host_sync_barriers)
    for mod in project.modules:
        barriers.update(mod.barrier_defs)
    for mod in project.modules:
        if not mod.is_hot(project.config):
            continue
        np_names = _numpy_names(mod.tree)
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_last_name(node)
            if name in barriers:
                continue  # the sanctioned barrier itself
            reason = _sync_reason(node, np_names)
            if not reason:
                continue
            if not astutil.in_loop(node, mod.parents):
                continue
            symbol = astutil.enclosing_symbol(node, mod.parents)
            if symbol.split(".")[-1] in barriers:
                continue  # inside a barrier's own implementation
            if _inside_barrier_call(node, mod.parents, barriers):
                continue
            yield mod.finding(
                "MPT005",
                node,
                f"host sync in a hot-path loop: {reason} — batch it "
                "outside the loop or go through force_completion at a "
                "measured boundary",
            )
