"""MPT001 — collective called with a literal axis name the module never binds.

``lax.psum(x, "dp")`` deadlocks (or fails to lower) unless some enclosing
``shard_map``/``Mesh`` binds the axis ``"dp"``. Functions that take the axis
as a *parameter* (the repo convention — ``def step(..., axis): lax.psum(g,
axis)``) are exempt by construction: only string literals are checked, and a
literal is fine when the same module also names that axis in a
``shard_map``/``Mesh``/``axis_names=`` context (module granularity — the
linter doesn't do interprocedural binding analysis, it catches the "copied a
collective out of its mesh context" class of bug).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from mpit_tpu.analysis import astutil

RULES = {
    "MPT001": (
        "unbound-collective-axis",
        "lax.psum-family call with a literal axis name not bound by any "
        "shard_map/Mesh context in the module",
    ),
}

COLLECTIVE_FNS = {
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "ppermute",
    "pshuffle",
    "axis_index",
    "axis_size",
}

# calls whose string constants (specs, axis_names tuples...) bind axis names.
# P/PartitionSpec/NamedSharding count: a module that writes P("pp") specs is
# evidently feeding them to a mesh that has the axis, even when the Mesh
# itself is constructed elsewhere (the pipeline trainer pattern).
_BINDING_CALLS = {"shard_map", "Mesh", "AbstractMesh", "make_mesh",
                  "create_device_mesh", "init", "P", "PartitionSpec",
                  "NamedSharding"}
_BINDING_KEYWORDS = {"axis_names", "axis_name"}


def _bound_axes(tree: ast.Module) -> set:
    bound = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = astutil.call_last_name(node)
            if name in _BINDING_CALLS:
                bound.update(astutil.string_constants(node))
        if isinstance(node, ast.keyword) and node.arg in _BINDING_KEYWORDS:
            bound.update(astutil.string_constants(node.value))
    return bound


def _jax_prefixed(dotted: str, module_imports_lax_names: set) -> bool:
    parts = dotted.split(".")
    if len(parts) == 1:
        return parts[0] in module_imports_lax_names
    return "lax" in parts[:-1] or parts[0] == "jax"


def _lax_imports(tree: ast.Module) -> set:
    """Names imported straight from jax.lax (``from jax.lax import psum``) —
    the only way a BARE collective call is jax's rather than a local
    helper's."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "jax.lax",
            "jax._src.lax.parallel",
        ):
            for alias in node.names:
                out.add(alias.asname or alias.name)
    return out


def _axis_literals(arg: ast.AST) -> Iterator[str]:
    """String literal(s) in an axis argument (a name or a tuple of names)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        yield arg.value
    elif isinstance(arg, (ast.Tuple, ast.List)):
        for elt in arg.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                yield elt.value


def run(project) -> Iterable:
    for mod in project.modules:
        bound = _bound_axes(mod.tree)
        bare_ok = _lax_imports(mod.tree)
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            dotted = astutil.dotted_name(node.func)
            if dotted is None:
                continue
            if dotted.split(".")[-1] not in COLLECTIVE_FNS:
                continue
            if not _jax_prefixed(dotted, bare_ok):
                continue
            axis_arg = astutil.get_arg(node, 1, "axis_name")
            if axis_arg is None:
                axis_arg = astutil.get_arg(node, 1, "axis")
            if axis_arg is None and dotted.split(".")[-1] in (
                "axis_index",
                "axis_size",
            ):
                axis_arg = astutil.get_arg(node, 0, "axis_name")
            if axis_arg is None:
                continue
            for lit in _axis_literals(axis_arg):
                if lit not in bound:
                    yield mod.finding(
                        "MPT001",
                        node,
                        f"collective {dotted!r} names axis {lit!r}, which "
                        "no shard_map/Mesh context in this module binds — "
                        "outside an SPMD context this deadlocks or fails "
                        "to lower",
                    )
