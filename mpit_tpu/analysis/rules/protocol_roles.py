"""MPT008 — protocol role divergence across the pserver/pclient boundary.

The cross-rank half of the RT102 story, caught before anything runs. Using
the role models from :mod:`mpit_tpu.analysis.protocol` (markered modules,
tags resolved through the module graph), three statically-decidable
divergence shapes are flagged:

- **unpaired send**: a role sends a concrete tag its counterpart can
  neither recv concretely nor route through a wildcard-recv dispatch
  branch. The message parks in the peer's mailbox forever — at best a
  leak, at worst (the pserver's ``else: raise``) a crash, and either way
  the roles' protocols have drifted apart;
- **unpaired recv**: a role blocks in ``recv`` on a concrete tag the
  counterpart never sends — a guaranteed hang at the first call;
- **cross-wait**: function f in role A recvs tag T1 *before* sending T2,
  while function g in role B recvs T2 before sending T1. Each side's recv
  is satisfied only by the other's later send: the classic head-of-line
  protocol deadlock, decidable from the two orderings alone.

Conservatism: tags that don't resolve to integers are skipped; a
counterpart with a wildcard recv but NO visible dispatch comparisons is
assumed to handle everything (we can't see its routing); roles whose
counterpart is outside the scan set are not checked. A dispatch branch for
a tag nobody sends is dead code, not a divergence, and is deliberately NOT
flagged (the wildcard recv never blocks on it).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from mpit_tpu.analysis import protocol

RULES = {
    "MPT008": (
        "protocol-role-divergence",
        "send/recv tag sets or orders of paired protocol roles have "
        "drifted apart — unpaired tags park or hang, crossed orders "
        "deadlock",
    ),
}


def _anchor(op: protocol.ProtoOp) -> ast.AST:
    node = ast.Constant(op.tag)
    node.lineno, node.col_offset = op.line, op.col
    return node


def _emit(by_rel, op: protocol.ProtoOp, message: str):
    mod = by_rel.get(op.rel)
    if mod is not None:
        f = mod.finding("MPT008", _anchor(op), message)
        # the synthetic anchor has no parents entry; the ProtoOp already
        # carries the real enclosing symbol
        yield dataclasses.replace(f, symbol=op.symbol)


def _unpaired_sends(role, cp, by_rel) -> Iterable:
    blind_dispatcher = cp.has_wildcard_recv and not cp.dispatch_tags
    if blind_dispatcher:
        return
    seen = set()
    for op in role.sends:
        if op.tag in cp.handled_tags or op.tag in seen:
            continue
        if op.tag in role.handled_tags:
            # intra-role traffic: the SENDING role's own dispatch handles
            # this tag (peer-to-peer exchange between instances of one
            # role, e.g. server->server shard handoff) — the counterpart
            # never needs a branch for it
            continue
        seen.add(op.tag)  # one finding per divergent tag, not per site
        yield from _emit(
            by_rel,
            op,
            f"role {role.role!r} sends {op.tag_text} (tag {op.tag}) but "
            f"counterpart role {cp.role!r} has no recv or dispatch branch "
            "for it — the message parks in the peer's mailbox (or trips "
            "its unknown-tag path) forever",
        )


def _unpaired_recvs(role, cp, by_rel) -> Iterable:
    seen = set()
    for op in role.concrete_recvs:
        if op.tag in cp.sent_tags or op.tag in seen:
            continue
        seen.add(op.tag)
        yield from _emit(
            by_rel,
            op,
            f"role {role.role!r} blocks in recv on {op.tag_text} "
            f"(tag {op.tag}) but counterpart role {cp.role!r} never sends "
            "it — this recv can never complete",
        )


def _cross_waits(role, cp, by_rel) -> Iterable:
    """recv(T1)-before-send(T2) in one role vs recv(T2)-before-send(T1)
    in the counterpart: neither side can make progress."""
    for f_ops in role.sequences().values():
        for i, r1 in enumerate(f_ops):
            if r1.kind != "recv" or r1.is_wildcard:
                continue
            later_sends = {
                op.tag for op in f_ops[i + 1 :] if op.kind == "send"
            }
            if not later_sends:
                continue
            for g_ops in cp.sequences().values():
                for k, r2 in enumerate(g_ops):
                    if (
                        r2.kind != "recv"
                        or r2.is_wildcard
                        or r2.tag not in later_sends
                    ):
                        continue
                    if any(
                        op.kind == "send" and op.tag == r1.tag
                        for op in g_ops[k + 1 :]
                    ):
                        yield from _emit(
                            by_rel,
                            r1,
                            f"cross-wait deadlock: {role.role!r}."
                            f"{r1.symbol} recvs tag {r1.tag} before "
                            f"sending tag {r2.tag}, while {cp.role!r}."
                            f"{r2.symbol} recvs tag {r2.tag} before "
                            f"sending tag {r1.tag} — neither side can "
                            "reach the send the other is blocked on",
                        )
                        break
                else:
                    continue
                break


def run(project) -> Iterable:
    roles = project.roles
    by_rel = {m.rel: m for m in project.modules}
    for role in roles.values():
        cp = roles.get(role.counterpart)
        if cp is None:
            continue  # counterpart outside the scan set: nothing checkable
        yield from _unpaired_sends(role, cp, by_rel)
        yield from _unpaired_recvs(role, cp, by_rel)
        if role.role < cp.role:  # one report per role pair
            yield from _cross_waits(role, cp, by_rel)
            yield from _cross_waits(cp, role, by_rel)
