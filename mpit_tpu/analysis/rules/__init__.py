"""Rule registry for the static linter.

Each rule module exposes ``run(project) -> Iterable[Finding]`` plus the
``RULES`` metadata it owns. Adding a rule = adding a module here and
registering it in ``RULE_MODULES`` (and documenting it in
``docs/ANALYSIS.md``).
"""

from __future__ import annotations

from mpit_tpu.analysis.rules import (
    collectives,
    concurrency,
    fleet_check,
    host_sync,
    jit_signature,
    locks,
    metric_names,
    model_check,
    numerics_flow,
    payload_schema,
    protocol_roles,
    tags,
    wire_format,
)

RULE_MODULES = (
    collectives,
    tags,
    jit_signature,
    host_sync,
    locks,
    wire_format,
    protocol_roles,
    model_check,
    fleet_check,
    metric_names,
    concurrency,
    payload_schema,
    numerics_flow,
)

# rule id -> (title, one-line rationale); the CLI's --list-rules output and
# the docs table are generated from this single source
RULE_DOCS = {}
for _mod in RULE_MODULES:
    RULE_DOCS.update(_mod.RULES)
