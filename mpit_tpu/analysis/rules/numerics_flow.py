"""MPT020-022: precision-flow rules over the numerics model
(:mod:`mpit_tpu.analysis.numerics`, ``project.numerics``).

MPT020 flags an accumulation (``sum``/``mean``/``psum``/...) whose
operand the dataflow proves to be quantized CODES — raw wire
representation, not values. Summing int8 codes adds scaled integers
without their scales; summing bf16 code halves adds uint16 bit patterns.
Both paths must dequantize (or explicitly ``astype(float32)`` + scale)
first: the collectives' f32-accumulate invariant.

MPT021 flags a lossy quantize on the training push/exchange path (its
codes provably reach a ``send``/collective wire hop) whose residual
``x - dequantize(quantize(x))`` is never folded back into error-feedback
state — here, or in the one caller level the model tracks. Without the
fold the quantization error is *dropped* every round instead of
re-injected, which turns an unbiased compressor into a biased one (see
docs/WIRE.md). Deliberately stateless paths (serving weight pushes, the
ZeRO scatter) carry an explicit ``# mpit-analysis: ef-off[reason]``
marker on the quantize line: the design decision is an annotation in the
code, not a baseline entry.

MPT022 flags mode/scale provenance mismatches: int8 codes reaching a
dequant declared bf16 (or vice versa), an int8 dequant whose scale is
``None`` (dropped) or provably from a *different* quantize site
(reused), and a wire tag whose inferred payload precision drifts from
the ``precision`` column in ``wire-schema.lock.json``.

All three inherit the model's resolve-or-skip discipline: an unresolved
mode, a multi-origin value, or an escape into unmodeled code produces no
claim. The dynamic complement is RT104 (``MPIT_RT_NUMERICS=1``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional

from mpit_tpu.analysis.findings import Finding

RULES = {
    "MPT020": (
        "accumulation over quantized codes",
        "a sum/mean/psum reduces bf16/int8 wire codes instead of "
        "dequantized f32 values — bit patterns and unscaled integers "
        "accumulate, silently producing garbage gradients",
    ),
    "MPT021": (
        "unpaired error feedback on a lossy push path",
        "a quantize whose codes reach the wire never folds its residual "
        "x - dequantize(quantize(x)) back into EF state — the "
        "compression error is dropped every round, biasing the update "
        "(mark intentional paths with '# mpit-analysis: ef-off[reason]')",
    ),
    "MPT022": (
        "quantization mode/scale provenance mismatch",
        "codes are dequantized with a mode or scale they were not built "
        "with (or a wire tag's precision drifts from the lockfile) — "
        "the reconstruction is numerically unrelated to the input",
    ),
}


def _emit(by_rel, rule, site, message) -> Optional[Finding]:
    mod = by_rel.get(site.rel)
    if mod is None:
        return None
    anchor = ast.Pass()
    anchor.lineno = site.line
    anchor.col_offset = site.col
    f = mod.finding(rule, anchor, message)
    return dataclasses.replace(f, symbol=site.symbol)


def _mpt020(model, by_rel) -> Iterable[Finding]:
    for r in model.reduce_sites:
        f = _emit(
            by_rel,
            "MPT020",
            r.site,
            f"{r.func}() accumulates {r.operand} — raw wire codes, not "
            "values; reduce over the f32 reconstruction (dequantize "
            "first), never over the wire representation",
        )
        if f is not None:
            yield f


def _mpt021(model, by_rel) -> Iterable[Finding]:
    for q in model.quant_sites:
        if q.ef != "unpaired":
            # paired, ef-off-marked, purely local, or escaping into
            # unmodeled code (no claim) — only a proven sent-and-never-
            # folded site is a finding
            continue
        f = _emit(
            by_rel,
            "MPT021",
            q.site,
            f"{q.func}({q.mode or '?'}) codes reach the wire but the "
            "residual x - dequantize(quantize(x)) is never folded into "
            "error-feedback state — the compression error is dropped "
            "every round (pair it, or mark the site "
            "'# mpit-analysis: ef-off[reason]' if statelessness is the "
            "design)",
        )
        if f is not None:
            yield f


def _mpt022(model, by_rel) -> Iterable[Finding]:
    for d in model.dequant_sites:
        if (
            d.declared_mode is not None
            and d.codes_mode is not None
            and d.declared_mode != d.codes_mode
        ):
            f = _emit(
                by_rel,
                "MPT022",
                d.site,
                f"{d.func}() declares mode {d.declared_mode!r} but its "
                f"codes were built by a {d.codes_mode!r} quantize at "
                f"{d.codes_origin.short() if d.codes_origin else '?'} — "
                "the reconstruction decodes the wrong representation",
            )
            if f is not None:
                yield f
            continue  # one claim per site: the mode confusion subsumes
            # whatever the scale argument looks like
        if d.codes_mode == "int8" and d.scale_is_none:
            f = _emit(
                by_rel,
                "MPT022",
                d.site,
                f"{d.func}() drops the scale (None) for int8 codes "
                f"built at "
                f"{d.codes_origin.short() if d.codes_origin else '?'} — "
                "int8 reconstruction without its absmax scale is "
                "meaningless",
            )
            if f is not None:
                yield f
            continue
        if d.scale_origin is not None and d.codes_origin is not None:
            f = _emit(
                by_rel,
                "MPT022",
                d.site,
                f"{d.func}() pairs codes from "
                f"{d.codes_origin.short()} with a scale from "
                f"{d.scale_origin.short()} — a scale reused across "
                "chunks reconstructs with the wrong magnitude",
            )
            if f is not None:
                yield f
    for tag, ent in sorted(model.tag_precision.items()):
        if ent["site"] is None or ent["locked"] is None:
            continue
        if ent["inferred"] == ent["locked"]:
            continue
        f = _emit(
            by_rel,
            "MPT022",
            ent["site"],
            f"{ent['name']} payload precision drifted: senders now "
            f"carry {ent['inferred'] or ['(none)']} but "
            f"wire-schema.lock.json pins {ent['locked'] or ['(none)']} "
            "— update the lock (schema --update-lock) if the precision "
            "change is intended",
        )
        if f is not None:
            yield f


def run(project) -> Iterable[Finding]:
    model = project.numerics
    by_rel = {m.rel: m for m in project.modules}
    out: List[Finding] = []
    out.extend(_mpt020(model, by_rel))
    out.extend(_mpt021(model, by_rel))
    out.extend(_mpt022(model, by_rel))
    return out
