"""MPT007 — pickle protocol drift at a transport boundary.

The wire format is ``length + pickle(payload)`` and both brokers (socket
and native) must keep emitting the SAME pickle protocol: readers
auto-detect (the protocol id is embedded in the stream, which is why
``pickle.loads`` has nothing to pin and is not checked), but a *writer*
that drifts — a module hard-coding a different number, omitting
``protocol=`` (the interpreter default moves across versions), or passing
``pickle.HIGHEST_PROTOCOL``/``-1`` (explicitly version-dependent) — makes
frames that a mixed-version peer may not parse, and the failure is a
corrupted-looking stream on the OTHER rank, far from the bad dumps call.

The canonical protocol is the ``WIRE_PICKLE_PROTOCOL`` constant in
``transport/socket_transport.py`` (taken from the scan set when covered,
else from the installed package next to this rule — never imported).
Checked only at transport boundaries: modules under a ``transport/`` or
``native/`` path component (``Config.wire_parts``), or any module carrying
a ``# mpit-analysis: wire-boundary`` marker comment. Every ``pickle.dumps``
there must pin ``protocol=`` to the canonical constant *by name* — a
literal equal to the canonical value is still flagged, because a future
bump of the constant would silently strand it.

The binary framing codec (docs/WIRE.md) has the identical drift surface:
frame *readers* dispatch on the version byte in the preamble (nothing to
pin), but a frame *writer* — any ``encode_frame`` call at a wire
boundary — that omits ``version=`` or pins something other than the
``WIRE_FORMAT_VERSION`` constant in ``transport/wire.py`` produces frames
a peer may reject, and again the failure surfaces as a decode error on
the OTHER rank. Same rule id, same boundary set, same by-name
requirement; the canonical constant is located the same way
(``Config.wire_version_name`` / ``wire_format_version`` override).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path, PurePosixPath
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

RULES = {
    "MPT007": (
        "pickle-protocol-drift",
        "wire writer at a transport boundary (pickle.dumps protocol= or "
        "encode_frame version=) that is absent, literal, "
        "interpreter-dependent, or resolves to a value other than the "
        "canonical wire constant",
    ),
}

WIRE_MARKER_RE = re.compile(r"#\s*mpit-analysis:\s*wire-boundary")

_CANONICAL_REL_SUFFIX = "transport/socket_transport.py"
_CANONICAL_FRAME_REL_SUFFIX = "transport/wire.py"
_VERSION_DEPENDENT = {"HIGHEST_PROTOCOL", "DEFAULT_PROTOCOL"}


def _pickle_dumps_names(tree: ast.Module) -> tuple:
    """(module aliases of ``pickle``, bare names bound to ``dumps``)."""
    mod_aliases, fn_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "pickle":
                    mod_aliases.add(alias.asname or "pickle")
        elif isinstance(node, ast.ImportFrom) and node.module == "pickle":
            for alias in node.names:
                if alias.name == "dumps":
                    fn_names.add(alias.asname or "dumps")
    return mod_aliases, fn_names


def _is_dumps_call(call: ast.Call, mod_aliases, fn_names) -> bool:
    dotted = astutil.dotted_name(call.func)
    if dotted is None:
        return False
    parts = dotted.split(".")
    if len(parts) == 1:
        return parts[0] in fn_names
    return parts[-1] == "dumps" and parts[0] in mod_aliases


def _canonical_constant(
    project, rel_suffix: str, name: str, override
) -> Optional[tuple]:
    """(value, constant name, where) for a canonical wire constant, or
    None when it can't be located (then nothing is checked — there is no
    contract to drift from)."""
    if override is not None:
        return int(override), name, "config override"
    graph = project.graph
    for mod in project.modules:
        if not mod.rel.endswith(rel_suffix):
            continue
        info = graph.module_for_rel(mod.rel)
        if info is not None and name in info.constants:
            return info.constants[name], name, mod.rel
    # scan set doesn't cover the transport: fall back to the installed
    # package relative to this file (parsed, never imported)
    canon = Path(__file__).resolve().parents[2] / PurePosixPath(rel_suffix)
    try:
        tree = ast.parse(canon.read_text())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == name:
                val = astutil.int_constant(node.value)
                if val is not None:
                    return val, name, "mpit_tpu/" + rel_suffix
    return None


def canonical_protocol(project) -> Optional[tuple]:
    """(value, constant name, where) for the wire's canonical pickle
    protocol (``transport/socket_transport.py``)."""
    return _canonical_constant(
        project,
        _CANONICAL_REL_SUFFIX,
        project.config.wire_protocol_name,
        project.config.wire_pickle_protocol,
    )


def canonical_wire_version(project) -> Optional[tuple]:
    """(value, constant name, where) for the binary frame version
    (``transport/wire.py``)."""
    return _canonical_constant(
        project,
        _CANONICAL_FRAME_REL_SUFFIX,
        project.config.wire_version_name,
        project.config.wire_format_version,
    )


def _encode_frame_names(tree: ast.Module) -> tuple:
    """(aliases naming the wire codec module, bare names bound to
    ``encode_frame``). Recognizes every import spelling in use: ``import
    mpit_tpu.transport.wire [as w]``, ``from mpit_tpu.transport import
    wire [as w]``, ``from [mpit_tpu.transport.]wire import encode_frame
    [as f]`` — including relative forms (``from . import wire``)."""
    mod_aliases, fn_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "wire" or alias.name.endswith(".wire"):
                    mod_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m == "wire" or m.endswith(".wire"):
                for alias in node.names:
                    if alias.name == "encode_frame":
                        fn_names.add(alias.asname or "encode_frame")
            else:
                for alias in node.names:
                    if alias.name == "wire":
                        mod_aliases.add(alias.asname or "wire")
    return mod_aliases, fn_names


def _is_encode_frame_call(call: ast.Call, mod_aliases, fn_names) -> bool:
    dotted = astutil.dotted_name(call.func)
    if dotted is None:
        return False
    parts = dotted.split(".")
    if len(parts) == 1:
        return parts[0] in fn_names
    return (
        parts[-1] == "encode_frame"
        and ".".join(parts[:-1]) in mod_aliases
    )


def _check_encode_frame(
    mod, info, graph, call, canon_value, canon_name, where
):
    """Mirror of :func:`_check_dumps` for frame writers: ``version=`` is
    keyword-only on ``encode_frame`` and must name the canonical
    constant. (Readers dispatch on the preamble's version byte — nothing
    to pin — so only ``encode_frame`` is checked.)"""
    ver = astutil.get_arg(call, 3, "version")
    if ver is None:
        yield mod.finding(
            "MPT007",
            call,
            "frame writer without version= — a codec bump would change "
            "what this site emits underneath its peers; pin "
            f"version={canon_name} (={canon_value}, {where})",
        )
        return
    lit = astutil.int_constant(ver)
    if lit is not None:
        if lit != canon_value:
            yield mod.finding(
                "MPT007",
                call,
                f"frame version drift: encode_frame pins version={lit} "
                f"but the wire contract is {canon_name}={canon_value} "
                f"({where}) — peers negotiate against the canonical "
                "version and will reject these frames",
            )
        else:
            yield mod.finding(
                "MPT007",
                call,
                f"encode_frame hard-codes version={lit}; it matches "
                f"{canon_name} today, but a bump of the constant would "
                f"silently strand this site — use {canon_name} itself",
            )
        return
    dotted = astutil.dotted_name(ver)
    if dotted is None:
        return  # dynamic expression: out of static scope
    resolved = graph.resolve_constant(info, ver)
    if resolved is None:
        if dotted.split(".")[-1] != canon_name:
            yield mod.finding(
                "MPT007",
                call,
                f"encode_frame version= names {dotted!r}, which does "
                f"not resolve to the wire contract {canon_name}="
                f"{canon_value} ({where})",
            )
    elif resolved != canon_value:
        yield mod.finding(
            "MPT007",
            call,
            f"frame version drift: {dotted} resolves to {resolved} but "
            f"the wire contract is {canon_name}={canon_value} ({where})",
        )


def _is_wire_module(mod, config) -> bool:
    parts = PurePosixPath(mod.rel).parts[:-1]
    if any(p in config.wire_parts for p in parts):
        return True
    # real COMMENT tokens only — this rule's own docstring quotes the
    # marker; substring scan first so unmarked modules skip the tokenize
    if not any("wire-boundary" in ln for ln in mod.source_lines):
        return False
    return any(
        WIRE_MARKER_RE.search(text)
        for _, text in astutil.iter_comments(mod.source_lines)
    )


def _check_dumps(mod, info, graph, call, canon_value, canon_name, where):
    proto = astutil.get_arg(call, 1, "protocol")
    if proto is None:
        yield mod.finding(
            "MPT007",
            call,
            "pickle.dumps on the wire without protocol= — the "
            "interpreter default drifts across versions; pin "
            f"protocol={canon_name} (={canon_value}, {where})",
        )
        return
    lit = astutil.int_constant(proto)
    if lit is not None:
        if lit == -1:
            yield mod.finding(
                "MPT007",
                call,
                "pickle.dumps(protocol=-1) is interpreter-dependent "
                f"(highest available) — pin protocol={canon_name} "
                f"(={canon_value})",
            )
        elif lit != canon_value:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle protocol drift: dumps pins protocol={lit} but "
                f"the wire contract is {canon_name}={canon_value} "
                f"({where}) — mixed ranks on one socket corrupt frames "
                "silently",
            )
        else:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle.dumps hard-codes protocol={lit}; it matches "
                f"{canon_name} today, but a bump of the constant would "
                f"silently strand this site — use {canon_name} itself",
            )
        return
    dotted = astutil.dotted_name(proto)
    if dotted is None:
        return  # dynamic expression: out of static scope
    if dotted.split(".")[-1] in _VERSION_DEPENDENT:
        yield mod.finding(
            "MPT007",
            call,
            f"pickle.dumps(protocol={dotted}) is interpreter-dependent "
            f"— pin protocol={canon_name} (={canon_value})",
        )
        return
    resolved = graph.resolve_constant(info, proto)
    if resolved is None:
        # unresolvable name: accept only the canonical spelling (covers
        # linting a single file whose import chain is off the scan set)
        if dotted.split(".")[-1] != canon_name:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle.dumps protocol= names {dotted!r}, which does "
                f"not resolve to the wire contract {canon_name}="
                f"{canon_value} ({where})",
            )
    elif resolved != canon_value:
        yield mod.finding(
            "MPT007",
            call,
            f"pickle protocol drift: {dotted} resolves to {resolved} "
            f"but the wire contract is {canon_name}={canon_value} "
            f"({where})",
        )


def run(project) -> Iterable:
    pkl = canonical_protocol(project)
    frm = canonical_wire_version(project)
    if pkl is None and frm is None:
        return
    graph = project.graph
    for mod in project.modules:
        if not _is_wire_module(mod, project.config):
            continue
        p_mods, p_fns = _pickle_dumps_names(mod.tree)
        f_mods, f_fns = _encode_frame_names(mod.tree)
        if not (p_mods or p_fns or f_mods or f_fns):
            continue
        info = graph.module_for_rel(mod.rel)
        for node in mod.nodes:
            if not isinstance(node, ast.Call):
                continue
            if pkl is not None and _is_dumps_call(node, p_mods, p_fns):
                yield from _check_dumps(mod, info, graph, node, *pkl)
            elif frm is not None and _is_encode_frame_call(
                node, f_mods, f_fns
            ):
                yield from _check_encode_frame(
                    mod, info, graph, node, *frm
                )
