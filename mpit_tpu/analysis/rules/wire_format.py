"""MPT007 — pickle protocol drift at a transport boundary.

The wire format is ``length + pickle(payload)`` and both brokers (socket
and native) must keep emitting the SAME pickle protocol: readers
auto-detect (the protocol id is embedded in the stream, which is why
``pickle.loads`` has nothing to pin and is not checked), but a *writer*
that drifts — a module hard-coding a different number, omitting
``protocol=`` (the interpreter default moves across versions), or passing
``pickle.HIGHEST_PROTOCOL``/``-1`` (explicitly version-dependent) — makes
frames that a mixed-version peer may not parse, and the failure is a
corrupted-looking stream on the OTHER rank, far from the bad dumps call.

The canonical protocol is the ``WIRE_PICKLE_PROTOCOL`` constant in
``transport/socket_transport.py`` (taken from the scan set when covered,
else from the installed package next to this rule — never imported).
Checked only at transport boundaries: modules under a ``transport/`` or
``native/`` path component (``Config.wire_parts``), or any module carrying
a ``# mpit-analysis: wire-boundary`` marker comment. Every ``pickle.dumps``
there must pin ``protocol=`` to the canonical constant *by name* — a
literal equal to the canonical value is still flagged, because a future
bump of the constant would silently strand it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path, PurePosixPath
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

RULES = {
    "MPT007": (
        "pickle-protocol-drift",
        "pickle.dumps at a transport boundary whose protocol= is absent, "
        "literal, interpreter-dependent, or resolves to a value other "
        "than the canonical wire constant",
    ),
}

WIRE_MARKER_RE = re.compile(r"#\s*mpit-analysis:\s*wire-boundary")

_CANONICAL_REL_SUFFIX = "transport/socket_transport.py"
_VERSION_DEPENDENT = {"HIGHEST_PROTOCOL", "DEFAULT_PROTOCOL"}


def _pickle_dumps_names(tree: ast.Module) -> tuple:
    """(module aliases of ``pickle``, bare names bound to ``dumps``)."""
    mod_aliases, fn_names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "pickle":
                    mod_aliases.add(alias.asname or "pickle")
        elif isinstance(node, ast.ImportFrom) and node.module == "pickle":
            for alias in node.names:
                if alias.name == "dumps":
                    fn_names.add(alias.asname or "dumps")
    return mod_aliases, fn_names


def _is_dumps_call(call: ast.Call, mod_aliases, fn_names) -> bool:
    dotted = astutil.dotted_name(call.func)
    if dotted is None:
        return False
    parts = dotted.split(".")
    if len(parts) == 1:
        return parts[0] in fn_names
    return parts[-1] == "dumps" and parts[0] in mod_aliases


def canonical_protocol(project) -> Optional[tuple]:
    """(value, constant name, where) for the wire's canonical pickle
    protocol, or None when it can't be located (then nothing is checked —
    there is no contract to drift from)."""
    name = project.config.wire_protocol_name
    override = project.config.wire_pickle_protocol
    if override is not None:
        return int(override), name, "config override"
    graph = project.graph
    for mod in project.modules:
        if not mod.rel.endswith(_CANONICAL_REL_SUFFIX):
            continue
        info = graph.module_for_rel(mod.rel)
        if info is not None and name in info.constants:
            return info.constants[name], name, mod.rel
    # scan set doesn't cover the transport: fall back to the installed
    # package relative to this file (parsed, never imported)
    canon = Path(__file__).resolve().parents[2] / "transport" / (
        "socket_transport.py"
    )
    try:
        tree = ast.parse(canon.read_text())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and tgt.id == name:
                val = astutil.int_constant(node.value)
                if val is not None:
                    return val, name, "mpit_tpu/" + _CANONICAL_REL_SUFFIX
    return None


def _is_wire_module(mod, config) -> bool:
    parts = PurePosixPath(mod.rel).parts[:-1]
    if any(p in config.wire_parts for p in parts):
        return True
    # real COMMENT tokens only — this rule's own docstring quotes the marker
    return any(
        WIRE_MARKER_RE.search(text)
        for _, text in astutil.iter_comments(mod.source_lines)
    )


def _check_dumps(mod, info, graph, call, canon_value, canon_name, where):
    proto = astutil.get_arg(call, 1, "protocol")
    if proto is None:
        yield mod.finding(
            "MPT007",
            call,
            "pickle.dumps on the wire without protocol= — the "
            "interpreter default drifts across versions; pin "
            f"protocol={canon_name} (={canon_value}, {where})",
        )
        return
    lit = astutil.int_constant(proto)
    if lit is not None:
        if lit == -1:
            yield mod.finding(
                "MPT007",
                call,
                "pickle.dumps(protocol=-1) is interpreter-dependent "
                f"(highest available) — pin protocol={canon_name} "
                f"(={canon_value})",
            )
        elif lit != canon_value:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle protocol drift: dumps pins protocol={lit} but "
                f"the wire contract is {canon_name}={canon_value} "
                f"({where}) — mixed ranks on one socket corrupt frames "
                "silently",
            )
        else:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle.dumps hard-codes protocol={lit}; it matches "
                f"{canon_name} today, but a bump of the constant would "
                f"silently strand this site — use {canon_name} itself",
            )
        return
    dotted = astutil.dotted_name(proto)
    if dotted is None:
        return  # dynamic expression: out of static scope
    if dotted.split(".")[-1] in _VERSION_DEPENDENT:
        yield mod.finding(
            "MPT007",
            call,
            f"pickle.dumps(protocol={dotted}) is interpreter-dependent "
            f"— pin protocol={canon_name} (={canon_value})",
        )
        return
    resolved = graph.resolve_constant(info, proto)
    if resolved is None:
        # unresolvable name: accept only the canonical spelling (covers
        # linting a single file whose import chain is off the scan set)
        if dotted.split(".")[-1] != canon_name:
            yield mod.finding(
                "MPT007",
                call,
                f"pickle.dumps protocol= names {dotted!r}, which does "
                f"not resolve to the wire contract {canon_name}="
                f"{canon_value} ({where})",
            )
    elif resolved != canon_value:
        yield mod.finding(
            "MPT007",
            call,
            f"pickle protocol drift: {dotted} resolves to {resolved} "
            f"but the wire contract is {canon_name}={canon_value} "
            f"({where})",
        )


def run(project) -> Iterable:
    canon = canonical_protocol(project)
    if canon is None:
        return
    canon_value, canon_name, where = canon
    graph = project.graph
    for mod in project.modules:
        if not _is_wire_module(mod, project.config):
            continue
        mod_aliases, fn_names = _pickle_dumps_names(mod.tree)
        if not mod_aliases and not fn_names:
            continue
        info = graph.module_for_rel(mod.rel)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_dumps_call(
                node, mod_aliases, fn_names
            ):
                yield from _check_dumps(
                    mod, info, graph, node, canon_value, canon_name, where
                )
