"""MPT002/MPT003 — transport tag discipline.

The PS protocol's tags (``TAG_FETCH``.. in ``parallel/pserver.py``) are the
wire contract: mpiT's dominant failure class is a misused tag silently
routing a message to the wrong consumer (SURVEY.md §5). Two rules:

- MPT002: a hard-coded *literal* tag at a transport ``send``/``isend``/
  ``recv``/``irecv``/``probe`` call site. Literal tags bypass the registry,
  so nothing stops two modules from claiming the same integer — use a
  ``TAG_*`` constant. (``-1`` = ANY_TAG is exempt: it's a wildcard, not a
  claim.)
- MPT003: two ``TAG_*`` constants with the same value in different modules
  (or two names for one value inside a module) — a tag collision against
  the registry extracted from ``parallel/``. Distinct protocol roles
  sharing an integer corrupt each other's mailboxes the moment they share
  a broker.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable

from mpit_tpu.analysis import astutil

RULES = {
    "MPT002": (
        "literal-transport-tag",
        "transport send/recv call site with a hard-coded literal tag "
        "instead of a TAG_* registry constant",
    ),
    "MPT003": (
        "tag-collision",
        "two TAG_* constants share one integer value across modules — "
        "colliding protocol roles corrupt each other's mailboxes",
    ),
}

_TAG_NAME_RE = re.compile(r"^TAG_[A-Z0-9_]+$")

# (attr name, positional index of the tag argument)
_SEND_SITES = {"send": 1, "isend": 1}
_RECV_SITES = {"recv": 1, "irecv": 1, "probe": 1}


@dataclasses.dataclass(frozen=True)
class TagDef:
    name: str
    value: int
    rel: str
    line: int


def _module_tags(tree: ast.Module, rel: str) -> list:
    out = []
    for node in tree.body:  # module level only: the registry convention
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name) and _TAG_NAME_RE.match(tgt.id):
                val = astutil.int_constant(node.value)
                if val is not None:
                    out.append(TagDef(tgt.id, val, rel, node.lineno))
    return out


def _canonical_registry() -> list:
    """TAG_* defs from the installed mpit_tpu/parallel package — the
    protocol's source of truth, included even when the scan path doesn't
    cover it (a plugin module claiming TAG_FETCH's value must collide).
    Located relative to THIS file, never imported: importing the parallel
    package would initialize jax, and the linter must stay runnable in
    bare containers (see lint.py's module docstring)."""
    pdir = Path(__file__).resolve().parents[2] / "parallel"
    if not pdir.is_dir():
        return []
    out = []
    for py in sorted(pdir.glob("*.py")):
        try:
            tree = ast.parse(py.read_text())
        except (OSError, SyntaxError):
            continue
        out.extend(_module_tags(tree, f"mpit_tpu/parallel/{py.name}"))
    return out


def iter_literal_tag_sites(tree: ast.Module) -> Iterable:
    """(call node, tag literal node, value) for every MPT002-shaped site —
    shared by the rule (findings) and ``--fix`` (rewrites)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_last_name(node)
        if name in _SEND_SITES:
            # transport sends are (dst, tag, payload): 3+ args keeps
            # socket.send(bytes) and queue.send(x) out of scope
            if len(node.args) + len(node.keywords) < 3:
                continue
            tag_arg = astutil.get_arg(node, _SEND_SITES[name], "tag")
        elif name in _RECV_SITES:
            tag_arg = astutil.get_arg(node, _RECV_SITES[name], "tag")
        else:
            continue
        if tag_arg is None:
            continue
        val = astutil.int_constant(tag_arg)
        if val is None or val == -1:  # ANY_TAG wildcard
            continue
        yield node, tag_arg, val


def _literal_tag_findings(mod) -> Iterable:
    for node, _tag_arg, val in iter_literal_tag_sites(mod.tree):
        name = astutil.call_last_name(node)
        yield mod.finding(
            "MPT002",
            node,
            f"literal tag {val} at a transport {name}() site — use a "
            "TAG_* constant from the protocol registry so collisions "
            "are checkable",
        )


def run(project) -> Iterable:
    defs: list = []
    scanned_keys = set()
    by_rel = {}
    for mod in project.modules:
        tags = _module_tags(mod.tree, mod.rel)
        defs.extend(tags)
        by_rel[mod.rel] = mod
        scanned_keys.update(
            (Path(t.rel).name, t.name) for t in tags
        )
        yield from _literal_tag_findings(mod)

    if project.config.canonical_tag_registry:
        for t in _canonical_registry():
            # don't double-count a file present in both the scan set and
            # the installed package (the self-check case)
            if (Path(t.rel).name, t.name) not in scanned_keys:
                defs.append(t)

    by_value: dict = {}
    for t in defs:
        by_value.setdefault(t.value, []).append(t)
    for value, group in sorted(by_value.items()):
        if len({(t.rel, t.name) for t in group}) < 2:
            continue
        # report at each definition site inside the scan set
        for t in group:
            mod = by_rel.get(t.rel)
            if mod is None:
                continue  # canonical-registry-only side of the collision
            others = ", ".join(
                f"{o.name} ({o.rel}:{o.line})"
                for o in group
                if (o.rel, o.name) != (t.rel, t.name)
            )
            node = ast.Constant(value)
            node.lineno, node.col_offset = t.line, 0
            yield mod.finding(
                "MPT003",
                node,
                f"{t.name} = {value} collides with {others} — distinct "
                "protocol roles must not share a tag value",
            )
