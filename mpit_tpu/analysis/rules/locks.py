"""MPT006 — blocking transport/socket call made while holding a lock.

The deadlock shape the runtime checker (RT101) hunts dynamically, caught at
the source: a ``sendall``/``connect``/``recv`` that can block indefinitely
inside a ``with <lock>:`` body serializes every other thread needing that
lock behind one slow peer — and if the blocked peer needs a lock the stalled
thread holds, the process deadlocks. The socket transport's *per-destination*
send lock is the deliberate, baselined exception (one slow rank must not
stall traffic to healthy ranks, and the per-dst lock guarantees exactly
that isolation); a NEW blocking call under the outbound-cache or any other
shared lock fails the build.

Heuristic: a ``with`` item whose expression's last name component contains
``lock`` (case-insensitive, ``cond`` excluded — condition-variable waits
are the documented sleep-holding-the-lock pattern) guards the body; any
call in the body whose final attribute is a known blocking primitive is
flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

RULES = {
    "MPT006": (
        "blocking-call-under-lock",
        "indefinitely-blocking socket/transport call inside a held "
        "threading.Lock — serializes peers and risks deadlock",
    ),
}

_BLOCKING = {
    "sendall",
    "connect",
    "create_connection",
    "accept",
    "recv",
    "irecv",
    "send",
    "isend",
    "wait",
    "join",
}
# .send is only transport/socket-shaped with these arg counts (socket.send
# takes bytes; transport send takes (dst, tag, payload))
_SEND_MIN_ARGS = {"send": 1, "isend": 1}


def _lockish_name(expr: ast.AST) -> Optional[str]:
    """The guarding name if ``expr`` looks like a lock acquisition."""
    cur = expr
    if isinstance(cur, ast.Call):
        cur = cur.func  # self._dst_lock(dst)
    if isinstance(cur, ast.Subscript):
        cur = cur.value  # self._locks[i]
    name = None
    if isinstance(cur, ast.Attribute):
        name = cur.attr
    elif isinstance(cur, ast.Name):
        name = cur.id
    if name is None:
        return None
    low = name.lower()
    if "cond" in low:
        return None
    return name if "lock" in low or "mutex" in low else None


def run(project) -> Iterable:
    for mod in project.modules:
        for node in mod.nodes:
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            guards = [
                n
                for n in (
                    _lockish_name(item.context_expr) for item in node.items
                )
                if n
            ]
            if not guards:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                name = astutil.call_last_name(sub)
                if name not in _BLOCKING:
                    continue
                if name in _SEND_MIN_ARGS and (
                    len(sub.args) + len(sub.keywords)
                    < _SEND_MIN_ARGS[name]
                ):
                    continue
                if name == "join" and len(sub.args) == 1:
                    continue  # "sep".join(parts) — the str method
                yield mod.finding(
                    "MPT006",
                    sub,
                    f"{name}() can block indefinitely while "
                    f"{guards[0]!r} is held — every thread needing the "
                    "lock stalls behind the slowest peer (move the "
                    "blocking I/O outside the critical section or use a "
                    "per-peer lock)",
                )
