"""MPT004 — ``jax.jit`` static/donate argument drift vs the wrapped signature.

The exact failure class of commit c166392: a function gains or loses a
parameter, the ``static_argnums`` tuple on its jit wrapper silently keeps
pointing at the old positions, and the first symptom is an AOT-lowering
failure (or, worse, a tracer leaking into a hash-based cache key) far from
the edit. Checked statically:

- every index in ``static_argnums``/``donate_argnums`` must be a valid
  positional index of the wrapped function (skipped when it takes
  ``*args``);
- every name in ``static_argnames``/``donate_argnames`` must be a
  parameter name (skipped when it takes ``**kwargs``).

Covered shapes — the direct sites, plus the wrapper chains the module
graph can see through (cross-module, via imports and aliases):

- ``@jax.jit(...)`` / ``@functools.partial(jax.jit, ...)`` decorators;
- ``f = jax.jit(target, ...)`` where ``target`` resolves through any chain
  of aliases, ``functools.partial`` links (each link SHIFTS the positional
  frame: ``partial(g, x)`` consumes ``g``'s first parameter, so index 0 of
  the jitted callable is ``g``'s second), and pure pass-through wrappers
  (``def w(*a, **k): return g(*a, **k)``);
- bare decorators that resolve to a jit factory: either an assignment
  ``jit_static = functools.partial(jax.jit, static_argnums=...)`` or a def
  whose body returns ``jax.jit(<its first parameter>, static_argnums=...)``
  — every ``@jit_static`` application is checked against the decorated
  function's signature, wherever the factory lives.

Non-literal index/name expressions are skipped (no constant folding), as
is any chain the graph cannot resolve (star imports, dynamic dispatch) —
conservative in the no-finding direction.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil
from mpit_tpu.analysis.graph import CallableInfo

RULES = {
    "MPT004": (
        "jit-static-drift",
        "jit static_argnums/static_argnames (or donate_*) out of range / "
        "not in the wrapped function's signature (wrapper chains "
        "included)",
    ),
}

_INDEX_KW = ("static_argnums", "donate_argnums")
_NAME_KW = ("static_argnames", "donate_argnames")
_JIT_NAMES = {"jit"}  # jax.jit, jax.api.jit, bare jit from jax import


def _is_jit(func: ast.AST) -> bool:
    dotted = astutil.dotted_name(func)
    return dotted is not None and dotted.split(".")[-1] in _JIT_NAMES


def _jit_keywords(call: ast.Call) -> Optional[list]:
    """The keyword list of a jit wrapper call, for both spellings:
    ``jax.jit(fn, ...)`` and ``functools.partial(jax.jit, ...)``."""
    if _is_jit(call.func):
        return call.keywords
    dotted = astutil.dotted_name(call.func)
    if (
        dotted is not None
        and dotted.split(".")[-1] == "partial"
        and call.args
        and isinstance(call.args[0], (ast.Attribute, ast.Name))
        and _is_jit(call.args[0])
    ):
        return call.keywords
    return None


def _int_tuple(node: ast.AST) -> Optional[list]:
    single = astutil.int_constant(node)
    if single is not None:
        return [single]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            v = astutil.int_constant(elt)
            if v is None:
                return None  # non-literal member: skip the whole check
            out.append(v)
        return out
    return None


def _str_tuple(node: ast.AST) -> Optional[list]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def _check(mod, site: ast.AST, keywords: list, target: CallableInfo):
    """Validate static/donate kwargs against the resolved callable's
    EFFECTIVE signature (positional frame shifted past partial-bound
    leading parameters; keyword-bound names removed)."""
    fn = target.fn
    pos_all = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    pos_params = pos_all[target.bound_pos :]
    all_names = (
        set(pos_params) | {a.arg for a in fn.args.kwonlyargs}
    ) - target.bound_names
    has_varargs = fn.args.vararg is not None
    has_varkw = fn.args.kwarg is not None
    via = (
        f" (reached through a {target.depth}-link wrapper chain)"
        if target.depth
        else ""
    )
    for kw in keywords:
        if kw.arg in _INDEX_KW and not has_varargs:
            idxs = _int_tuple(kw.value)
            for idx in idxs or ():
                if not 0 <= idx < len(pos_params):
                    yield mod.finding(
                        "MPT004",
                        site,
                        f"{kw.arg} index {idx} out of range for "
                        f"{fn.name}() with {len(pos_params)} positional "
                        f"parameters{via} — signature drifted under its "
                        "jit wrapper (the c166392 failure class)",
                    )
        elif kw.arg in _NAME_KW and not has_varkw:
            names = _str_tuple(kw.value)
            for name in names or ():
                if name not in all_names:
                    yield mod.finding(
                        "MPT004",
                        site,
                        f"{kw.arg} names {name!r}, which is not a "
                        f"parameter of {fn.name}(){via} — signature "
                        "drifted under its jit wrapper",
                    )


def _factory_jit_kws(fn) -> Optional[list]:
    """static/donate keyword list of a decorator factory: a def whose body
    returns ``jax.jit(<its first parameter>, ...kwargs...)``."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    if not params:
        return None
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Return)
            and isinstance(node.value, ast.Call)
        ):
            continue
        call = node.value
        if not _is_jit(call.func):
            continue
        if (
            call.args
            and isinstance(call.args[0], ast.Name)
            and call.args[0].id == params[0]
        ):
            return call.keywords
    return None


def _decorator_factory_kws(graph, info, dec: ast.AST) -> Optional[list]:
    """kwargs applied by a BARE decorator (``@jit_static``) that resolves
    to a jit factory — a partial-of-jit assignment or a factory def."""
    dotted = astutil.dotted_name(dec)
    if dotted is None:
        return None
    if dotted.split(".")[-1] in _JIT_NAMES:
        return None  # plain @jax.jit with no kwargs: nothing to check
    r = graph.resolve(info, dotted)
    if r is None:
        return None
    if r.kind == "assign" and isinstance(r.value, ast.Call):
        return _jit_keywords(r.value)
    if r.kind == "function":
        return _factory_jit_kws(r.value)
    return None


def _local_callable(local_defs, graph, info, node) -> Optional[CallableInfo]:
    """Resolve a jit target: function-scope defs first (the trainer
    pattern — ``jax.jit(step)`` right under ``def step`` in a method),
    then the module graph's alias/partial/wrapper chains."""
    if isinstance(node, ast.Name) and node.id in local_defs:
        fn = local_defs[node.id]
        return CallableInfo(fn=fn, module=info, bound_pos=0)
    if graph is None:
        return None
    return graph.resolve_callable(info, node)


def run(project) -> Iterable:
    graph = project.graph
    for mod in project.modules:
        info = graph.module_for_rel(mod.rel)
        # every def in the module by bare name (function-scope included),
        # for jit-assignment targets the graph's module-level view misses
        local_defs = {
            n.name: n
            for n in mod.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in mod.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                target = CallableInfo(fn=node, module=info)
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        kws = _jit_keywords(dec)
                        if kws:
                            yield from _check(mod, dec, kws, target)
                    else:
                        kws = _decorator_factory_kws(graph, info, dec)
                        if kws:
                            yield from _check(mod, dec, kws, target)
            elif isinstance(node, ast.Assign):
                if not isinstance(node.value, ast.Call):
                    continue
                call = node.value
                if not (_is_jit(call.func) and call.args):
                    continue
                resolved = _local_callable(
                    local_defs, graph, info, call.args[0]
                )
                if resolved is not None:
                    yield from _check(mod, call, call.keywords, resolved)
