"""MPT004 — ``jax.jit`` static/donate argument drift vs the wrapped signature.

The exact failure class of commit c166392: a function gains or loses a
parameter, the ``static_argnums`` tuple on its jit wrapper silently keeps
pointing at the old positions, and the first symptom is an AOT-lowering
failure (or, worse, a tracer leaking into a hash-based cache key) far from
the edit. Checked statically:

- every index in ``static_argnums``/``donate_argnums`` must be a valid
  positional index of the wrapped function (skipped when it takes
  ``*args``);
- every name in ``static_argnames``/``donate_argnames`` must be a
  parameter name (skipped when it takes ``**kwargs``).

Covered shapes: ``@jax.jit(...)`` / ``@functools.partial(jax.jit, ...)``
decorators, and module-level ``f = jax.jit(g, static_argnums=...)``
assignments where ``g`` is a def in the same module. Non-literal index/name
expressions are skipped (no constant folding).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from mpit_tpu.analysis import astutil

RULES = {
    "MPT004": (
        "jit-static-drift",
        "jit static_argnums/static_argnames (or donate_*) out of range / "
        "not in the wrapped function's signature",
    ),
}

_INDEX_KW = ("static_argnums", "donate_argnums")
_NAME_KW = ("static_argnames", "donate_argnames")
_JIT_NAMES = {"jit"}  # jax.jit, jax.api.jit, bare jit from jax import


def _is_jit(func: ast.AST) -> bool:
    dotted = astutil.dotted_name(func)
    return dotted is not None and dotted.split(".")[-1] in _JIT_NAMES


def _jit_keywords(call: ast.Call) -> Optional[list]:
    """The keyword list of a jit wrapper call, for both spellings:
    ``jax.jit(fn, ...)`` and ``functools.partial(jax.jit, ...)``."""
    if _is_jit(call.func):
        return call.keywords
    dotted = astutil.dotted_name(call.func)
    if (
        dotted is not None
        and dotted.split(".")[-1] == "partial"
        and call.args
        and isinstance(call.args[0], (ast.Attribute, ast.Name))
        and _is_jit(call.args[0])
    ):
        return call.keywords
    return None


def _int_tuple(node: ast.AST) -> Optional[list]:
    single = astutil.int_constant(node)
    if single is not None:
        return [single]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            v = astutil.int_constant(elt)
            if v is None:
                return None  # non-literal member: skip the whole check
            out.append(v)
        return out
    return None


def _str_tuple(node: ast.AST) -> Optional[list]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                return None
            out.append(elt.value)
        return out
    return None


def _check(mod, site: ast.AST, keywords: list, fn: ast.FunctionDef):
    pos_params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    all_names = set(pos_params) | {a.arg for a in fn.args.kwonlyargs}
    has_varargs = fn.args.vararg is not None
    has_varkw = fn.args.kwarg is not None
    for kw in keywords:
        if kw.arg in _INDEX_KW and not has_varargs:
            idxs = _int_tuple(kw.value)
            for idx in idxs or ():
                if not 0 <= idx < len(pos_params):
                    yield mod.finding(
                        "MPT004",
                        site,
                        f"{kw.arg} index {idx} out of range for "
                        f"{fn.name}() with {len(pos_params)} positional "
                        "parameters — signature drifted under its jit "
                        "wrapper (the c166392 failure class)",
                    )
        elif kw.arg in _NAME_KW and not has_varkw:
            names = _str_tuple(kw.value)
            for name in names or ():
                if name not in all_names:
                    yield mod.finding(
                        "MPT004",
                        site,
                        f"{kw.arg} names {name!r}, which is not a "
                        f"parameter of {fn.name}() — signature drifted "
                        "under its jit wrapper",
                    )


def run(project) -> Iterable:
    for mod in project.modules:
        # module-level defs by name, for the assignment form
        defs = {
            n.name: n
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef)
        }
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    kws = _jit_keywords(dec)
                    if kws is not None:
                        yield from _check(mod, dec, kws, node)
            elif isinstance(node, ast.Assign):
                if not (
                    isinstance(node.value, ast.Call)
                    and _is_jit(node.value.func)
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Name)
                ):
                    continue
                fn = defs.get(node.value.args[0].id)
                if fn is not None:
                    yield from _check(
                        mod, node.value, node.value.keywords, fn
                    )
