"""MPT019 — model-checked fleet routing: no request lost under a kill.

The serving fleet (``mpit_tpu/fleet/``) speaks its own conversation —
ROUTE/REPLY between the router and its replicas — with its own failure
mode: a replica killed mid-request takes consumed-but-unreplied work
with it, and the request is lost unless the router both *notices* (a
timeout on its reply recv) and *recovers* (a redispatch send of the
route tag). :func:`mpit_tpu.analysis.protocol.extract_fleet_semantics`
lifts those two facts out of the marked fleet roles;
:func:`mpit_tpu.analysis.mcheck.check_fleet` exhaustively explores the
fleet-route configuration (1 router x 2 replicas, bounded requests, one
replica kill allowed anywhere except the last survivor) and reports any
reachable state where a routed request is stranded on a dead replica
with no enabled recovery — the model form of the soak gate's "every
``req_route`` reaches ``req_finish`` or ``req_redispatch``" invariant.

Conservatism mirrors MPT009–011: no fleet roles in the scan set (or an
unextractable pair) means skip, never guess; a reported violation is a
real trace of the extracted model, and the finding carries the explored
state count as its exhaustiveness receipt. Results are memoized on the
frozen semantics, so the suite's repeated ``run_lint`` calls pay for the
exploration once per process.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from mpit_tpu.analysis import mcheck, protocol

RULES = {
    "MPT019": (
        "fleet-request-lost",
        "a single-replica-kill schedule exists where a routed serving "
        "request is neither finished nor redispatched — admitted work "
        "is silently lost",
    ),
}

# frozen FleetModelSemantics -> CheckResult, one exploration per process
_CACHE: dict = {}


def _anchor(line: int, col: int) -> ast.AST:
    node = ast.Constant(0)
    node.lineno, node.col_offset = line, col
    return node


def results_for(fsem: protocol.FleetSemantics) -> mcheck.CheckResult:
    key = mcheck.fleet_from_protocol(fsem)
    if key not in _CACHE:
        _CACHE[key] = mcheck.check_fleet(key, mcheck.fleet_config(quick=True))
    return _CACHE[key]


def run(project) -> Iterable:
    fsem: Optional[protocol.FleetSemantics] = (
        protocol.extract_fleet_semantics(project)
    )
    if fsem is None or fsem.route_send is None:
        return
    res = results_for(fsem)
    by_rel = {m.rel: m for m in project.modules}
    op = fsem.route_send  # the router's route dispatch pins the finding
    mod = by_rel.get(op.rel)
    if mod is None:
        return
    messages = [
        res.violations[rule]
        + f" (exhaustive: {res.states} states, "
        f"{res.fault_points} single-fault schedules)"
        for rule in sorted(res.violations)
    ]
    if res.truncated:
        messages.append(
            f"[{res.config.label}] state space exceeded "
            f"{res.config.max_states} states — exploration truncated, "
            "lost-request freedom NOT established"
        )
    for message in messages:
        f = mod.finding(
            "MPT019", _anchor(op.line, op.col), message
        )
        yield dataclasses.replace(f, symbol=op.symbol)
