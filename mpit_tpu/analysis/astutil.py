"""Small AST helpers shared by the lint rules (stdlib-only, no jax import —
the linter must run in a bare CI container and never initialize a backend).
"""

from __future__ import annotations

import ast
import io
import tokenize
from typing import Iterator, Optional


def iter_comments(source_lines: list) -> Iterator[tuple]:
    """(lineno, text) for every real COMMENT token. Marker scans must use
    this rather than regexing raw lines: a marker QUOTED inside a docstring
    (e.g. this package documenting its own ``# mpit-analysis: ...`` syntax)
    is not an opt-in."""
    readline = io.StringIO("\n".join(source_lines) + "\n").readline
    try:
        for tok in tokenize.generate_tokens(readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def walk_and_parents(tree: ast.AST) -> tuple:
    """(flat node list in ``ast.walk`` order, child -> parent map), both in
    ONE traversal. Loaded once per module: a dozen rules each re-walking
    every tree is the dominant cost of the whole-package scan, so rules
    iterate ``mod.nodes`` instead."""
    nodes = [tree]
    parents: dict = {}
    for node in nodes:  # appending while indexing = the same BFS as walk
        for child in ast.iter_child_nodes(node):
            parents[child] = node
            nodes.append(child)
    return nodes, parents


def build_parents(tree: ast.AST) -> dict:
    """child node -> parent node, for upward walks (enclosing fn, loops)."""
    return walk_and_parents(tree)[1]


def enclosing_symbol(node: ast.AST, parents: dict) -> str:
    """Dotted qualname of the innermost enclosing def/class, or <module>."""
    names = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) if names else "<module>"


def dotted_name(func: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for nested Attribute/Name chains; None for anything
    whose base isn't a plain name (calls, subscripts...)."""
    parts = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_last_name(call: ast.Call) -> Optional[str]:
    """Last component of the callee: 'sendall' for x.y.sendall(...),
    'psum' for psum(...). None when the callee base is itself a call or
    subscript — but the final attribute still names the operation, so
    ``self._connection(dst).sendall(f)`` resolves to 'sendall'."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def string_constants(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


# arithmetic the folder evaluates; Pow is deliberately absent (a folded
# ``2 ** 10**6`` would eat the scan's memory budget for no lint value)
_BIN_FOLDS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitXor: lambda a, b: a ^ b,
}
_UNARY_FOLDS = {
    ast.USub: lambda a: -a,
    ast.UAdd: lambda a: +a,
    ast.Invert: lambda a: ~a,
}
#: folded results larger than this are abandoned (a registry tag or wire
#: constant is small; anything bigger is data, not protocol)
_FOLD_INT_BOUND = 1 << 63
_FOLD_STR_BOUND = 4096


def _fold_leaf(value) -> Optional[object]:
    if isinstance(value, bool):
        return None  # True == 1 but is not a tag
    if isinstance(value, (int, str)):
        return value
    return None


def fold_binop(op: ast.operator, left, right) -> Optional[object]:
    """``left <op> right`` for already-folded int/str operands, or None
    when the combination doesn't fold (mixed types, div-by-zero, huge
    results). Shared with the module graph so ``TAG_BASE + 1`` folds the
    same whether the operands are literals or cross-module constants."""
    if left is None or right is None:
        return None
    if isinstance(left, str) or isinstance(right, str):
        # concatenation is the one string fold protocols use ("obs" + "1"
        # wire-version strings); everything else stays unfolded
        if (
            isinstance(op, ast.Add)
            and isinstance(left, str)
            and isinstance(right, str)
            and len(left) + len(right) <= _FOLD_STR_BOUND
        ):
            return left + right
        return None
    fold = _BIN_FOLDS.get(type(op))
    if fold is None:
        return None
    try:
        out = fold(left, right)
    except (ZeroDivisionError, ValueError, OverflowError):
        return None
    if isinstance(out, int) and abs(out) > _FOLD_INT_BOUND:
        return None
    return out


def fold_unaryop(op: ast.unaryop, operand) -> Optional[object]:
    fold = _UNARY_FOLDS.get(type(op))
    if fold is None or not isinstance(operand, int) or isinstance(
        operand, bool
    ):
        return None
    return fold(operand)


def fold_constant(node: ast.AST) -> Optional[object]:
    """Evaluate a pure-literal int/str expression: constants plus the
    arithmetic/concatenation in ``_BIN_FOLDS``/``_UNARY_FOLDS`` —
    ``(1 << 4) | 2`` folds to 18, ``"obs" + "1"`` to ``"obs1"``. Names
    don't fold here (that's the module graph's job); None = no fold."""
    if isinstance(node, ast.Constant):
        return _fold_leaf(node.value)
    if isinstance(node, ast.UnaryOp):
        return fold_unaryop(node.op, fold_constant(node.operand))
    if isinstance(node, ast.BinOp):
        return fold_binop(
            node.op, fold_constant(node.left), fold_constant(node.right)
        )
    return None


def int_constant(node: ast.AST) -> Optional[int]:
    """The int value of a pure-literal expression (bools excluded) —
    a plain Constant, or folded arithmetic like ``-1`` or ``2 + 1``;
    else None."""
    val = fold_constant(node)
    return val if isinstance(val, int) else None


def get_arg(
    call: ast.Call, pos: int, kw: str
) -> Optional[ast.AST]:
    """Argument at positional index ``pos`` or keyword ``kw``."""
    if len(call.args) > pos and not any(
        isinstance(a, ast.Starred) for a in call.args[: pos + 1]
    ):
        return call.args[pos]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


def in_loop(node: ast.AST, parents: dict) -> bool:
    """Is ``node`` syntactically inside a for/while body, without an
    intervening function boundary (a closure DEFINED in a loop does not
    itself run per iteration)?"""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = parents.get(cur)
    return False


def line_text(source_lines: list, node: ast.AST) -> str:
    try:
        return source_lines[node.lineno - 1].strip()
    except (AttributeError, IndexError):
        return ""
