"""Small AST helpers shared by the lint rules (stdlib-only, no jax import —
the linter must run in a bare CI container and never initialize a backend).
"""

from __future__ import annotations

import ast
import io
import tokenize
from typing import Iterator, Optional


def iter_comments(source_lines: list) -> Iterator[tuple]:
    """(lineno, text) for every real COMMENT token. Marker scans must use
    this rather than regexing raw lines: a marker QUOTED inside a docstring
    (e.g. this package documenting its own ``# mpit-analysis: ...`` syntax)
    is not an opt-in."""
    readline = io.StringIO("\n".join(source_lines) + "\n").readline
    try:
        for tok in tokenize.generate_tokens(readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def build_parents(tree: ast.AST) -> dict:
    """child node -> parent node, for upward walks (enclosing fn, loops)."""
    parents: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing_symbol(node: ast.AST, parents: dict) -> str:
    """Dotted qualname of the innermost enclosing def/class, or <module>."""
    names = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names)) if names else "<module>"


def dotted_name(func: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for nested Attribute/Name chains; None for anything
    whose base isn't a plain name (calls, subscripts...)."""
    parts = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_last_name(call: ast.Call) -> Optional[str]:
    """Last component of the callee: 'sendall' for x.y.sendall(...),
    'psum' for psum(...). None when the callee base is itself a call or
    subscript — but the final attribute still names the operation, so
    ``self._connection(dst).sendall(f)`` resolves to 'sendall'."""
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def string_constants(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def int_constant(node: ast.AST) -> Optional[int]:
    """The int value of a Constant node (bools excluded), else None."""
    if (
        isinstance(node, ast.Constant)
        and isinstance(node.value, int)
        and not isinstance(node.value, bool)
    ):
        return node.value
    if (  # -1 parses as UnaryOp(USub, Constant(1))
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.USub)
        and isinstance(node.operand, ast.Constant)
        and isinstance(node.operand.value, int)
        and not isinstance(node.operand.value, bool)
    ):
        return -node.operand.value
    return None


def get_arg(
    call: ast.Call, pos: int, kw: str
) -> Optional[ast.AST]:
    """Argument at positional index ``pos`` or keyword ``kw``."""
    if len(call.args) > pos and not any(
        isinstance(a, ast.Starred) for a in call.args[: pos + 1]
    ):
        return call.args[pos]
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    return None


def in_loop(node: ast.AST, parents: dict) -> bool:
    """Is ``node`` syntactically inside a for/while body, without an
    intervening function boundary (a closure DEFINED in a loop does not
    itself run per iteration)?"""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = parents.get(cur)
    return False


def line_text(source_lines: list, node: ast.AST) -> str:
    try:
        return source_lines[node.lineno - 1].strip()
    except (AttributeError, IndexError):
        return ""
