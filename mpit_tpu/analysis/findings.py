"""Finding model + baseline bookkeeping for the distributed-correctness linter.

A finding is one rule violation at one source location. Findings are
compared against a checked-in *baseline* (accepted deviations — e.g. the
deliberate per-destination send-under-lock in the socket transport) via a
line-number-free fingerprint, so routine edits above a finding don't churn
the baseline: the fingerprint is (rule, path, enclosing symbol, normalized
source text), counted — two identical violations in one function baseline
as a count of 2.
"""

from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Iterable, Optional

BASELINE_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # e.g. "MPT001"
    path: str  # posix path relative to the scan root
    line: int
    col: int
    symbol: str  # enclosing function qualname, or "<module>"
    message: str
    text: str = ""  # the flagged source line, stripped

    @property
    def fingerprint(self) -> str:
        return "|".join((self.rule, self.path, self.symbol, self.text))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.symbol}] {self.message}"
        )


def load_baseline(path) -> Counter:
    """fingerprint -> accepted count. Missing file = empty baseline."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError:
        return Counter()
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: version {doc.get('version')!r} != "
            f"{BASELINE_VERSION} — regenerate with --write-baseline"
        )
    return Counter(doc.get("findings", {}))


def write_baseline(path, findings: Iterable[Finding]) -> None:
    counts = Counter(f.fingerprint for f in findings)
    doc = {
        "version": BASELINE_VERSION,
        "findings": dict(sorted(counts.items())),
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def new_findings(
    findings: Iterable[Finding], baseline: Optional[Counter]
) -> list[Finding]:
    """Findings not covered by the baseline.

    Per fingerprint, the first ``baseline[fp]`` occurrences are accepted and
    any surplus is new — so ADDING a second copy of a baselined violation
    still fails the build, while the original stays accepted."""
    if not baseline:
        return list(findings)
    seen: Counter = Counter()
    out = []
    for f in findings:
        seen[f.fingerprint] += 1
        if seen[f.fingerprint] > baseline.get(f.fingerprint, 0):
            out.append(f)
    return out
