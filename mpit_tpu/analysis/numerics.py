"""Whole-program precision-dataflow model (rules MPT020-022, `numerics` CLI).

The repo moves most of its bytes in reduced precision — quantized PS
pushes, EQuARX-style quantized collectives, quantized fleet weight
streaming — and three invariants keep that correct:

1. **accumulate in f32, never over codes** — a ``sum``/``mean``/``psum``
   whose operand is bf16/int8 *codes* (the raw wire representation)
   reduces bit patterns, not values (MPT020);
2. **every lossy training-path quantize pairs with error feedback** —
   the residual ``x - dequantize(quantize(x))`` must be folded back into
   EF state on the same stream, or declared stateless with an explicit
   ``# mpit-analysis: ef-off[reason]`` marker (MPT021);
3. **codes are dequantized with the mode and scale they were built
   with** — int8 codes reaching a bf16 dequant, a dropped scale, a scale
   borrowed from a different quantization, or a wire tag whose payload
   precision drifts from the lockfile's ``precision`` column (MPT022).

This pass tracks a small precision lattice (f32 reconstruction ≥
QuantArray/codes provenance ≥ unknown) through assignments, tuple
unpacking, the shared quant kernels (:mod:`mpit_tpu.quant`, numpy and
jnp faces), container construction, slicing/reshape passthroughs, and
collective wire hops. Like the schema pass it is resolve-or-skip: a
value the tracker cannot prove to be codes (or a mode it cannot resolve
to a literal) produces NO claim. One level of interprocedural flow is
modeled for error-feedback pairing: a function that *returns* the
dequantized reconstruction (``sent_deq``) delegates the pairing to its
callers, which are then checked for the ``x - sent`` fold — the
``_quant_allreduce_leaf`` / ``quantized_allreduce`` split.

The dynamic complement is RT104 in :mod:`mpit_tpu.analysis.runtime`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from typing import Dict, FrozenSet, List, Optional

from mpit_tpu.analysis import astutil

#: quantize kernels by callee last-name: "qarray" returns a QuantArray,
#: "pair" returns (codes, scale[s])
QUANT_FNS = {
    "quantize": "qarray",
    "quantize_jnp": "pair",
    "quantize_rows": "pair",
    "quantize_rows_jnp": "pair",
}
#: dequantize kernels: positional index of the declared-mode argument
#: (None = the host face, whose mode rides inside the QuantArray)
DEQUANT_FNS = {
    "dequantize": None,
    "dequantize_jnp": 2,
    "dequantize_rows": 2,
    "dequantize_rows_jnp": 2,
}
#: reducers/accumulators MPT020 guards (bare or attribute calls)
REDUCE_FNS = ("sum", "mean", "nansum", "prod", "psum", "pmean")
#: calls that put a value on the wire (sends and collective hops) — the
#: "training push/exchange path" predicate for MPT021; matching is by
#: callee last-name ("send" as a substring covers _send_with_retry etc.)
WIRE_COLLECTIVES = (
    "all_to_all",
    "all_gather",
    "psum_scatter",
    "ppermute",
)
#: shape-only methods that preserve a value's precision and provenance
PASSTHROUGH_METHODS = ("reshape", "copy", "ravel", "flatten", "transpose")

MODES = ("off", "bf16", "int8")

_EF_OFF_RE = re.compile(r"#\s*mpit-analysis:\s*ef-off\[([^\]]*)\]")


@dataclasses.dataclass(frozen=True)
class Site:
    rel: str
    line: int
    col: int
    symbol: str

    def short(self) -> str:
        return f"{self.rel}:{self.line}"


def _site(mod, node) -> Site:
    return Site(
        rel=mod.rel,
        line=getattr(node, "lineno", 0),
        col=getattr(node, "col_offset", 0),
        symbol=astutil.enclosing_symbol(node, mod.parents),
    )


@dataclasses.dataclass
class QuantSite:
    """One call into a quantize kernel, with its error-feedback verdict."""

    site: Site
    func: str
    mode: Optional[str]  # literal-resolved, else None
    paired: bool = False  # residual fold seen (here or in a caller)
    sent: bool = False  # value reaches a send/collective wire hop
    escaped: bool = False  # reconstruction/codes returned to callers
    ef_off: Optional[str] = None  # marker reason, when annotated

    @property
    def ef(self) -> str:
        if self.ef_off is not None:
            return "ef-off"
        if self.paired:
            return "paired"
        if self.sent:
            return "unpaired"
        if self.escaped:
            return "escapes"
        return "local"


@dataclasses.dataclass(frozen=True)
class DequantSite:
    site: Site
    func: str
    declared_mode: Optional[str]  # mode argument, literal-resolved
    codes_mode: Optional[str]  # provenance: the producing quantize's mode
    codes_origin: Optional[Site]
    scale_is_none: bool
    scale_origin: Optional[Site]  # quantize site the scale came from


@dataclasses.dataclass(frozen=True)
class ReduceSite:
    site: Site
    func: str
    operand: str  # "codes[int8]" / "codes[?]" / "qarray[bf16]" / "f32"


@dataclasses.dataclass
class NumericsModel:
    quant_sites: List[QuantSite] = dataclasses.field(default_factory=list)
    dequant_sites: List[DequantSite] = dataclasses.field(
        default_factory=list
    )
    reduce_sites: List[ReduceSite] = dataclasses.field(default_factory=list)
    # tag -> {"name", "inferred": [...], "locked": [...] | None,
    #         "site": Site | None} — the wire-tag precision ledger
    tag_precision: Dict[int, dict] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "quant_sites": [
                {
                    "site": q.site.short(),
                    "symbol": q.site.symbol,
                    "func": q.func,
                    "mode": q.mode or "?",
                    "ef": q.ef,
                    **(
                        {"ef_off_reason": q.ef_off}
                        if q.ef_off is not None
                        else {}
                    ),
                }
                for q in self.quant_sites
            ],
            "dequant_sites": [
                {
                    "site": d.site.short(),
                    "symbol": d.site.symbol,
                    "func": d.func,
                    "declared_mode": d.declared_mode or "?",
                    "codes_mode": d.codes_mode or "?",
                    "scale": "none" if d.scale_is_none else "carried",
                }
                for d in self.dequant_sites
            ],
            "reduce_sites": [
                {
                    "site": r.site.short(),
                    "symbol": r.site.symbol,
                    "func": r.func,
                    "operand": r.operand,
                }
                for r in self.reduce_sites
            ],
            "tags": {
                str(tag): {
                    "name": ent["name"],
                    "inferred": ent["inferred"],
                    "locked": ent["locked"],
                }
                for tag, ent in sorted(self.tag_precision.items())
            },
        }


# ---------------------------------------------------------------------------
# abstract values


@dataclasses.dataclass(frozen=True)
class _Val:
    """One abstract value in the precision lattice. ``origins`` carries
    the quantize-site identities whose codes/QuantArray this value IS
    (or contains); ``deq_of`` the sites whose f32 reconstruction it is —
    the Sub operand that closes the EF recurrence."""

    prec: str = "unknown"  # f32|codes|qarray|pair|scale|container|str|none
    mode: Optional[str] = None
    origins: FrozenSet[int] = frozenset()
    deq_of: FrozenSet[int] = frozenset()


_UNKNOWN = _Val()
_F32 = _Val(prec="f32")


@dataclasses.dataclass(frozen=True)
class _Escape:
    """One value escaping a function via return: tuple index (None for
    the whole value), the quant sites it carries as codes, and the sites
    it reconstructs."""

    index: Optional[int]
    origins: FrozenSet[int]
    deq_of: FrozenSet[int]


class _FnEval:
    """Order-preserving abstract evaluation of one function body (or the
    module toplevel). Claims only what it can trace: unknown swallows
    everything it cannot."""

    def __init__(self, builder, mod, fn_name: str):
        self.b = builder
        self.mod = mod
        self.fn_name = fn_name
        self.env: Dict[str, _Val] = {}
        self.escapes: List[_Escape] = []

    # -- statements ------------------------------------------------------

    def run(self, body) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, s) -> None:
        if isinstance(s, ast.Assign):
            val = self.eval(s.value)
            for tgt in s.targets:
                self._bind(tgt, val, s.value)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            self._bind(s.target, self.eval(s.value), s.value)
        elif isinstance(s, ast.AugAssign):
            self.eval(s.value)
            if isinstance(s.target, ast.Name):
                self.env[s.target.id] = _UNKNOWN
        elif isinstance(s, ast.Expr):
            self.eval(s.value)
        elif isinstance(s, ast.Return):
            self._escape(s.value)
        elif isinstance(s, (ast.If, ast.While)):
            self.eval(s.test)
            self.run(s.body)
            self.run(s.orelse)
        elif isinstance(s, ast.For):
            self.eval(s.iter)
            if isinstance(s.target, ast.Name):
                self.env[s.target.id] = _UNKNOWN
            self.run(s.body)
            self.run(s.orelse)
        elif isinstance(s, ast.With):
            for item in s.items:
                self.eval(item.context_expr)
            self.run(s.body)
        elif isinstance(s, ast.Try):
            self.run(s.body)
            for h in s.handlers:
                self.run(h.body)
            self.run(s.orelse)
            self.run(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.b.eval_function(self.mod, s)
        # everything else (imports, class defs, global...) carries no flow

    def _bind(self, tgt, val: _Val, value_node) -> None:
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            names = [
                e.id if isinstance(e, ast.Name) else None for e in tgt.elts
            ]
            if val.prec == "pair" and len(names) == 2:
                # codes, scale = quantize_*(x, mode)
                if names[0]:
                    self.env[names[0]] = _Val(
                        "codes", val.mode, val.origins
                    )
                if names[1]:
                    self.env[names[1]] = _Val(
                        "scale", val.mode, val.origins
                    )
                return
            # a call into a summarized local fn: place escaped values
            summ = self.b.call_escapes(self.mod, value_node)
            if summ is not None:
                for esc in summ:
                    if (
                        esc.index is not None
                        and esc.index < len(names)
                        and names[esc.index]
                    ):
                        self.env[names[esc.index]] = _Val(
                            "container",
                            None,
                            esc.origins,
                            esc.deq_of,
                        )
                for i, n in enumerate(names):
                    if n and n not in self.env:
                        self.env[n] = _UNKNOWN
                # leave names already bound by escapes alone
                for n in names:
                    if n and n not in self.env:
                        self.env[n] = _UNKNOWN
                return
            for n in names:
                if n:
                    self.env[n] = _UNKNOWN
            return
        # attribute/subscript stores: no tracking (self._x = ... is state
        # the schema/threads passes own)

    def _escape(self, value) -> None:
        if value is None:
            return
        if isinstance(value, (ast.Tuple, ast.List)):
            for i, el in enumerate(value.elts):
                v = self.eval(el)
                if v.origins or v.deq_of:
                    self.escapes.append(_Escape(i, v.origins, v.deq_of))
            return
        v = self.eval(value)
        if v.origins or v.deq_of:
            self.escapes.append(_Escape(None, v.origins, v.deq_of))

    # -- expressions -----------------------------------------------------

    def eval(self, node) -> _Val:
        if node is None:
            return _UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, _UNKNOWN)
        if isinstance(node, ast.Constant):
            if node.value is None:
                return _Val(prec="none")
            if isinstance(node.value, str):
                return _Val(prec="str", mode=node.value)
            return _UNKNOWN
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            origins: FrozenSet[int] = frozenset()
            deq: FrozenSet[int] = frozenset()
            for el in node.elts:
                v = self.eval(el)
                origins |= v.origins
                deq |= v.deq_of
            return _Val("container", None, origins, deq)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.Subscript):
            self.eval(node.slice)
            base = self.eval(node.value)
            # slicing/indexing preserves codes-ness and reconstruction
            if base.prec in ("codes", "qarray", "f32", "container"):
                return base
            return _UNKNOWN
        if isinstance(node, ast.BinOp):
            left, right = self.eval(node.left), self.eval(node.right)
            if isinstance(node.op, ast.Sub):
                # x - dequantize(quantize(x)): the EF fold. Either side
                # being a reconstruction closes the recurrence for the
                # quantize sites it reconstructs.
                for sid in left.deq_of | right.deq_of:
                    self.b.mark_paired(sid)
            if left.prec == "f32" and right.prec == "f32":
                return _F32
            return _UNKNOWN
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand)
            return _F32 if v.prec == "f32" else _UNKNOWN
        if isinstance(node, (ast.BoolOp, ast.Compare)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self.eval(sub)
            return _UNKNOWN
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            a, b = self.eval(node.body), self.eval(node.orelse)
            if a.prec == b.prec == "f32":
                return _F32
            return _UNKNOWN
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in node.generators:
                self.eval(gen.iter)
            v = self.eval(node.elt)
            return _Val("container", None, v.origins, v.deq_of)
        if isinstance(node, ast.DictComp):
            for gen in node.generators:
                self.eval(gen.iter)
            self.eval(node.key)
            v = self.eval(node.value)
            return _Val("container", None, v.origins, v.deq_of)
        if isinstance(node, ast.Attribute):
            self.eval(node.value)
            return _UNKNOWN
        # anything else: evaluate child expressions for their side
        # effects, claim nothing
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                self.eval(sub)
        return _UNKNOWN

    def _resolve_mode(self, node) -> Optional[str]:
        v = self.eval(node) if node is not None else _UNKNOWN
        if v.prec == "str" and v.mode in MODES:
            return v.mode
        return None

    def _call(self, call: ast.Call) -> _Val:
        name = astutil.call_last_name(call)
        argvals = [self.eval(a) for a in call.args]
        for kw in call.keywords:
            argvals.append(self.eval(kw.value))

        if name in QUANT_FNS and not self.b.locally_defined(
            self.mod, name
        ):
            mode = self._resolve_mode(astutil.get_arg(call, 1, "mode"))
            sid = self.b.register_quant(self.mod, call, name, mode)
            kind = QUANT_FNS[name]
            return _Val(kind, mode, frozenset((sid,)))

        if name in DEQUANT_FNS and not self.b.locally_defined(
            self.mod, name
        ):
            return self._dequant_call(call, name, argvals)

        if name in REDUCE_FNS:
            operand = argvals[0] if call.args else _UNKNOWN
            if operand.prec in ("codes", "qarray", "pair"):
                self.b.register_reduce(
                    self.mod, call, name or "?", operand
                )
            # an accumulation is a fresh value: provenance ends here
            return _F32 if operand.prec in ("f32",) else _UNKNOWN

        if name in WIRE_COLLECTIVES:
            for v in argvals:
                for sid in v.origins:
                    self.b.mark_sent(sid)
            # the wire hop moves codes between ranks, it does not change
            # what they are: first-arg passthrough
            return argvals[0] if argvals else _UNKNOWN

        if name and "send" in name.lower():
            for v in argvals:
                for sid in v.origins:
                    self.b.mark_sent(sid)
            return _UNKNOWN

        if name == "append" and isinstance(call.func, ast.Attribute):
            # parts.append((sid, q)): the container inherits q's
            # provenance, so a later send of `parts` is a send of q
            base = call.func.value
            if isinstance(base, ast.Name):
                have = self.env.get(base.id, _UNKNOWN)
                extra_o = frozenset().union(
                    *[v.origins for v in argvals] or [frozenset()]
                )
                extra_d = frozenset().union(
                    *[v.deq_of for v in argvals] or [frozenset()]
                )
                if extra_o or extra_d:
                    self.env[base.id] = _Val(
                        "container",
                        None,
                        have.origins | extra_o,
                        have.deq_of | extra_d,
                    )
            return _UNKNOWN

        if name in PASSTHROUGH_METHODS and isinstance(
            call.func, ast.Attribute
        ):
            return self.eval(call.func.value)

        if name == "astype" and isinstance(call.func, ast.Attribute):
            base = self.eval(call.func.value)
            dt = astutil.dotted_name(call.args[0]) if call.args else None
            if dt and dt.rsplit(".", 1)[-1] in (
                "float32",
                "float64",
                "float",
            ):
                # an explicit f32 upcast: stop claiming codes-ness (the
                # scale application is the caller's business now)
                return _Val("f32", deq_of=base.deq_of)
            return _UNKNOWN

        # a call into a local function whose returns were summarized:
        # the escaped provenance flows to the caller
        summ = self.b.call_escapes(self.mod, call)
        if summ is not None:
            origins: FrozenSet[int] = frozenset()
            deq: FrozenSet[int] = frozenset()
            for esc in summ:
                origins |= esc.origins
                deq |= esc.deq_of
            if origins or deq:
                return _Val("container", None, origins, deq)
        return _UNKNOWN

    def _dequant_call(self, call, name, argvals) -> _Val:
        mode_pos = DEQUANT_FNS[name]
        codes_v = argvals[0] if call.args else _UNKNOWN
        if mode_pos is None:
            # host face: dequantize(q) — the mode rides in the
            # QuantArray; mismatch is impossible by construction
            declared = codes_v.mode
            scale_is_none = False
            scale_v = codes_v
        else:
            declared = self._resolve_mode(
                astutil.get_arg(call, mode_pos, "mode")
            )
            scale_node = astutil.get_arg(call, 1, "scale")
            scale_is_none = isinstance(
                scale_node, ast.Constant
            ) and scale_node.value is None
            scale_v = self.eval(scale_node) if scale_node else _UNKNOWN
        codes_mode, codes_origin = self.b.origin_of(codes_v.origins)
        _, scale_origin = self.b.origin_of(scale_v.origins)
        self.b.register_dequant(
            self.mod,
            call,
            name,
            declared,
            codes_mode,
            codes_origin,
            scale_is_none,
            scale_origin,
            scale_same=(
                not scale_v.origins or scale_v.origins == codes_v.origins
            ),
        )
        return _Val(prec="f32", deq_of=codes_v.origins)


class _Builder:
    def __init__(self, project):
        self.project = project
        self.model = NumericsModel()
        # (rel, line, col) -> quant site id; ids index self._qsites
        self._qkeys: Dict[tuple, int] = {}
        self._qsites: List[QuantSite] = []
        self._dkeys: set = set()
        self._rkeys: set = set()
        self._local_defs: Dict[str, set] = {}
        self._ef_off: Dict[str, Dict[int, str]] = {}
        # fn name (per module) -> escapes, for the one-level caller pass
        self._summaries: Dict[str, Dict[str, List[_Escape]]] = {}
        self._shadow = False  # pass 2: re-eval callers, no new claims

    # -- module prep -----------------------------------------------------

    def tracked_modules(self) -> list:
        out = []
        for mod in self.project.modules:
            if not any("quant" in ln for ln in mod.source_lines):
                continue  # prefilter: codes only originate from the
                # quant kernels, so a module that never says "quant"
                # cannot contribute (the 5s whole-package pin)
            defs = {
                n.name
                for n in mod.nodes
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            if "quantize" in defs and "dequantize" in defs:
                continue  # the kernel module itself defines the contract
            self._local_defs[mod.rel] = defs
            self._ef_off[mod.rel] = self._markers(mod)
            out.append(mod)
        return out

    @staticmethod
    def _markers(mod) -> Dict[int, str]:
        out = {}
        for i, ln in enumerate(mod.source_lines, start=1):
            m = _EF_OFF_RE.search(ln)
            if m:
                out[i] = m.group(1).strip()
        return out

    def locally_defined(self, mod, name: str) -> bool:
        return name in self._local_defs.get(mod.rel, ())

    # -- site registry (idempotent: pass 2 re-evaluates callers) ---------

    def register_quant(self, mod, call, func, mode) -> int:
        key = (mod.rel, call.lineno, call.col_offset)
        sid = self._qkeys.get(key)
        if sid is None:
            site = _site(mod, call)
            reason = self._ef_off[mod.rel].get(
                call.lineno, self._ef_off[mod.rel].get(call.lineno - 1)
            )
            sid = len(self._qsites)
            self._qkeys[key] = sid
            self._qsites.append(
                QuantSite(site=site, func=func, mode=mode, ef_off=reason)
            )
        return sid

    def register_dequant(
        self,
        mod,
        call,
        func,
        declared,
        codes_mode,
        codes_origin,
        scale_is_none,
        scale_origin,
        scale_same,
    ) -> None:
        key = (mod.rel, call.lineno, call.col_offset)
        if key in self._dkeys:
            return
        self._dkeys.add(key)
        self.model.dequant_sites.append(
            DequantSite(
                site=_site(mod, call),
                func=func,
                declared_mode=declared,
                codes_mode=codes_mode,
                codes_origin=codes_origin,
                scale_is_none=scale_is_none,
                scale_origin=None if scale_same else scale_origin,
            )
        )

    def register_reduce(self, mod, call, func, operand: _Val) -> None:
        key = (mod.rel, call.lineno, call.col_offset)
        if key in self._rkeys:
            return
        self._rkeys.add(key)
        mode, _ = self.origin_of(operand.origins)
        label = "qarray" if operand.prec == "qarray" else "codes"
        self.model.reduce_sites.append(
            ReduceSite(
                site=_site(mod, call),
                func=func,
                operand=f"{label}[{mode or '?'}]",
            )
        )

    def mark_paired(self, sid: int) -> None:
        self._qsites[sid].paired = True

    def mark_sent(self, sid: int) -> None:
        self._qsites[sid].sent = True

    def origin_of(self, origins: FrozenSet[int]) -> tuple:
        """(mode, site) when provenance is a single quantize site with a
        resolved mode; (None, site-or-None) otherwise — no claim."""
        if len(origins) != 1:
            return None, None
        q = self._qsites[next(iter(origins))]
        return q.mode, q.site

    # -- function evaluation --------------------------------------------

    def eval_function(self, mod, fn) -> None:
        name = getattr(fn, "name", None) or "<module>"
        ev = _FnEval(self, mod, name)
        ev.run(fn.body if hasattr(fn, "body") else fn)
        if not self._shadow and ev.escapes and name != "<module>":
            self._summaries.setdefault(mod.rel, {}).setdefault(
                name, []
            ).extend(ev.escapes)
        # escaped sites: pairing is delegated to callers (pass 2); until
        # a caller pairs them they stay "escapes" — never a claim
        for esc in ev.escapes:
            for sid in esc.origins | esc.deq_of:
                self._qsites[sid].escaped = True

    def call_escapes(self, mod, node) -> Optional[List[_Escape]]:
        if not isinstance(node, ast.Call):
            return None
        name = astutil.call_last_name(node)
        if name is None:
            return None
        return self._summaries.get(mod.rel, {}).get(name)

    # -- drive -----------------------------------------------------------

    def build(self) -> NumericsModel:
        mods = self.tracked_modules()
        fns = []  # (mod, fn-node) in deterministic order
        for mod in mods:
            top = [
                s
                for s in mod.tree.body
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
            ]
            ev = _FnEval(self, mod, "<module>")
            ev.run(top)
            for s in mod.tree.body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fns.append((mod, s))
                elif isinstance(s, ast.ClassDef):
                    for m in s.body:
                        if isinstance(
                            m, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fns.append((mod, m))
        for mod, fn in fns:
            self.eval_function(mod, fn)
        # pass 2: one level of caller context for escaped provenance —
        # re-evaluate only functions that call a summarized name
        self._shadow = True
        for mod, fn in fns:
            names = self._summaries.get(mod.rel)
            if not names:
                continue
            if any(
                isinstance(n, ast.Call)
                and astutil.call_last_name(n) in names
                for n in ast.walk(fn)
            ):
                self.eval_function(mod, fn)
        self.model.quant_sites = list(self._qsites)
        self._tag_precision()
        return self.model

    def _tag_precision(self) -> None:
        """The wire-tag precision ledger: what the schema model infers
        per tag vs the lockfile's ``precision`` column (resolve-or-skip:
        no lock, no column, or no sender site in scan -> no entry)."""
        from mpit_tpu.analysis import lint as lint_mod
        from mpit_tpu.analysis import schema as schema_mod

        if not self.project.modules:
            return
        root = lint_mod.find_repo_root(self.project.modules[0].path)
        lock_path = (
            root / schema_mod.SCHEMA_LOCK_FILENAME
            if root is not None
            else None
        )
        if lock_path is None or not lock_path.exists():
            return
        try:
            locked = json.loads(lock_path.read_text())
        except (OSError, ValueError):
            return
        ltags = locked.get("tags", {})
        if not any("precision" in ent for ent in ltags.values()):
            return  # pre-precision lock: nothing to diff against
        schema = self.project.schema
        doc = schema.to_json()
        for key, ent in sorted(doc["tags"].items(), key=lambda kv: int(kv[0])):
            lt = ltags.get(key)
            if lt is None or "precision" not in lt:
                continue  # a tag the lock doesn't govern (fixtures)
            tag = int(key)
            senders = schema.senders.get(tag)
            site = None
            if senders:
                s0 = senders[0].site
                site = Site(s0.rel, s0.line, s0.col, s0.symbol)
            self.model.tag_precision[tag] = {
                "name": ent["name"] or f"tag {key}",
                "inferred": ent.get("precision", []),
                "locked": lt.get("precision"),
                "site": site,
            }


def build_model(project) -> NumericsModel:
    return _Builder(project).build()
