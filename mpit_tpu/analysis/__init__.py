"""mpit_tpu.analysis — distributed-correctness linter + runtime checker.

Two halves (ISSUE 1, cross-module pass ISSUE 2):

- a static AST pass over the package (:mod:`~mpit_tpu.analysis.lint`,
  rules MPT001–MPT008) catching the distributed/JAX hazard classes that
  have actually bitten this codebase: unbound collective axis names,
  transport-tag indiscipline, jit static-argument drift (commit c166392,
  wrapper chains included), host syncs in hot loops, blocking I/O under
  locks, pickle wire-format drift, and protocol-role divergence. The
  cross-module rules share a whole-program name-resolution index
  (:mod:`~mpit_tpu.analysis.graph`) and a protocol-role model
  (:mod:`~mpit_tpu.analysis.protocol`) — still AST-only, scanned code is
  never imported. The wire payload-schema model
  (:mod:`~mpit_tpu.analysis.schema`, rules MPT016–018, ``schema``
  subcommand) rides the same indexes: per-tag sender/receiver schemas
  gated against the checked-in ``wire-schema.lock.json``, with the
  differential codec fuzz gate (``fuzz`` subcommand,
  :mod:`mpit_tpu.transport.fuzz`) as its dynamic half;
- an opt-in runtime checker (:mod:`~mpit_tpu.analysis.runtime`, rules
  RT101/RT102) instrumenting the transport layer's locks and mailboxes for
  lock-order cycles and concurrent tag reuse.

CLI: ``python -m mpit_tpu.analysis [--format json|text] [--fix] [path]`` —
exits 0 when the scan matches the checked-in baseline; ``--fix`` first
rewrites mechanically-fixable MPT002 sites (known literal tag → ``TAG_*``
name + import). See ``docs/ANALYSIS.md``.

This ``__init__`` stays import-light (PEP 562 lazy attributes): the
transports import :mod:`~mpit_tpu.analysis.runtime` on their hot
construction path, and pulling the whole AST machinery in with it would tax
every process start.
"""

from __future__ import annotations

_LAZY = {
    "Config": ("mpit_tpu.analysis.lint", "Config"),
    "run_lint": ("mpit_tpu.analysis.lint", "run_lint"),
    "Finding": ("mpit_tpu.analysis.findings", "Finding"),
    "load_baseline": ("mpit_tpu.analysis.findings", "load_baseline"),
    "new_findings": ("mpit_tpu.analysis.findings", "new_findings"),
    "write_baseline": ("mpit_tpu.analysis.findings", "write_baseline"),
    "RuntimeChecker": ("mpit_tpu.analysis.runtime", "RuntimeChecker"),
    "RuntimeFinding": ("mpit_tpu.analysis.runtime", "RuntimeFinding"),
    "checking": ("mpit_tpu.analysis.runtime", "checking"),
    "make_lock": ("mpit_tpu.analysis.runtime", "make_lock"),
    "active_checker": ("mpit_tpu.analysis.runtime", "active_checker"),
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
