"""Trace conformance: replay obs journals against the extracted protocol.

The static half of this package proves properties of the protocol
*model* (:mod:`mpit_tpu.analysis.mcheck`); this module closes the loop
on real executions: ``python -m mpit_tpu.analysis conform <obs-dir>``
reads the per-rank ``obs_rank*.jsonl`` journals that
:class:`mpit_tpu.obs.telemetry.TelemetryTransport` writes (plus the
chaos ``faults*.jsonl`` log when present) and checks the observed run
against the same role model and fault semantics the linter and model
checker extracted from the source — turning every chaos soak and
``tests/test_obs.py`` run into a protocol audit.

Checked properties:

- **TC201 causality** — every traced recv names, via ``from_span``, a
  send that actually happened; the recv landed on that send's
  destination rank, from its source rank, with its tag; and the
  receiver's Lamport clock is strictly ahead of the sender's at the
  send (``clock.observe`` guarantees this — a violation means the
  journals are from different runs, hand-edited, or the envelope was
  mis-threaded);
- **TC202 stream conservation** — per ``(src, dst, tag)`` stream,
  ``sends_ok - lost - orphans <= recvs <= sends_ok + duplicated`` where
  ``sends_ok`` counts err-free journaled sends and the fault log
  supplies the loss/duplication allowances (no fault log = no
  allowance). ``orphans`` licenses one undrained reply per duplication
  fault on the *reverse request stream*: a duplicated FETCH makes the
  server send an extra PARAM, and when the duplicate lands after the
  requester's last round that reply is legitimately never received.
  More receives than explicable = phantom messages; fewer = messages
  lost with no fault to blame;
- **TC203 role conformance** — each rank's sent-tag alphabet fits
  inside ONE extracted role (a rank sending both FETCH and PARAM is
  playing client and server at once, which the role model forbids), and
  every tag on the wire belongs to the extracted protocol alphabet;
- **TC204 version monotonicity** — per server rank, the center
  ``version`` stamped into PARAM replies (journaled as
  ``param_version`` records by the dynamics plane) never decreases in
  journal order. Journals are per-rank monotone by construction, so a
  decrease means the version counter itself regressed — the staleness
  accounting built on it would be garbage. Vacuous for pre-dynamics
  journals (no ``param_version`` records).

Caveat: journals record what the *sampler* kept. Conformance needs the
complete event stream, so runs checked here must use ``sample=1`` (the
default for ``MPIT_OBS_DIR``-driven test runs); a sampled journal fails
TC202 honestly rather than silently passing.

Elastic runs (docs/ROBUSTNESS.md): the launcher's supervisor journals
membership transitions to ``membership.jsonl`` in the same directory.
When that file shows churn (``kill``/``respawn`` events), the checks
relax EXACTLY where preemption makes journals honest-but-incomplete —
a SIGKILLed process cannot flush its journal tail, so its in-flight
sends may be received with no surviving send record (TC201) and its
stream counts may not balance (TC202); both relaxations are scoped to
the churned ranks, every other rank stays fully checked. TC204 becomes
per-generation: a restored server resumes from its last snapshot, so
the version counter may legitimately step back across a ``gen`` bump
(the PARAM journal records carry ``gen``); within a generation it must
still never decrease.

Truncated journals: a journal may also declare ITSELF incomplete via
its ``journal_cap`` footer — cap mode drops the tail once
``MPIT_OBS_MAX_RECORDS`` is hit, ring mode (``MPIT_OBS_RING``) evicts
the head to keep the newest window. Ranks whose footer shows non-zero
drops/evictions get the same scoped licensing as churned ranks (a recv
may name an evicted send, streams touching them may not balance);
see :func:`truncated_ranks`. A footer with zero drops declares the
journal complete and licenses nothing.

Like the rest of the analysis package this module imports neither jax
nor the transport stack — journals are just files.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Optional

from mpit_tpu.analysis import protocol
from mpit_tpu.obs import merge

#: fault kinds whose message is delivered anyway (possibly late/mangled)
_DELIVERED_KINDS = {"delay", "corrupt", "truncate"}
#: fault kinds that add a delivery
_DUP_KINDS = {"duplicate"}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str  # TC201 | TC202 | TC203 | TC204
    detail: str

    def __str__(self) -> str:
        return f"{self.rule}: {self.detail}"


@dataclasses.dataclass
class ConformanceReport:
    journals: list
    events: int
    sends: int
    recvs: int
    faults: int
    violations: list
    churned: list = dataclasses.field(default_factory=list)
    truncated: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def load_membership(obs_dir: str) -> list:
    """Membership transition records from the launcher's supervisor
    journal (``membership.jsonl``); empty for non-elastic runs."""
    path = os.path.join(obs_dir, "membership.jsonl")
    if not os.path.exists(path):
        return []
    return [
        r for r in merge.read_journal(path)
        if r.get("ev") == "membership"
    ]


def churned_ranks(membership: list) -> frozenset:
    """Ranks that lost a process mid-run (killed or respawned) — the
    ranks whose journals are licensed to be incomplete."""
    return frozenset(
        r["rank"] for r in membership
        if r.get("kind") in ("kill", "respawn")
        and isinstance(r.get("rank"), int)
    )


def _load(obs_dir: str, faults_path: Optional[str]):
    paths = merge.expand_journal_paths([obs_dir])
    records = []
    for p in paths:
        records.extend(
            r for r in merge.read_journal(p) if r.get("ev") in
            ("send", "isend", "recv", "param_version", "journal_cap")
        )
    faults = merge.read_fault_log(faults_path or obs_dir)
    return paths, records, faults


def truncated_ranks(records: list) -> frozenset:
    """Ranks whose own journal declares itself incomplete via a
    ``journal_cap`` footer (written incrementally, so it survives even
    a SIGKILL): cap mode dropped the stream's TAIL
    (``dropped_records > 0``), ring mode evicted its HEAD
    (``evicted_records > 0``). Either way the rank's record set is an
    honest subset — license it exactly like a churned rank. A footer
    with zero drops/evictions declares the journal COMPLETE and earns
    no license. Unlike membership licensing this is never disabled by
    ``--strict``/``elastic=False``: the evidence is in the journal
    itself, not in a side file."""
    out = set()
    for r in records:
        if r.get("ev") != "journal_cap":
            continue
        if r.get("dropped_records", 0) or r.get("evicted_records", 0):
            rank = merge._rec_rank(r)
            if isinstance(rank, int):
                out.add(rank)
    return frozenset(out)


def _tc201_causality(
    records: list, churned: frozenset = frozenset()
) -> Iterable[Violation]:
    by_span = {}
    for r in records:
        if r["ev"] in ("send", "isend") and "span" in r:
            by_span[r["span"]] = r
    for r in records:
        if r["ev"] != "recv" or "from_span" not in r:
            continue
        src = merge._rec_rank(r)  # receiver rank
        s = by_span.get(r["from_span"])
        if s is None:
            if r.get("src") in churned:
                # the claimed sender lost a process mid-run: its journal
                # tail (including this send's record) died unflushed
                # with it — an honest gap, not an outside message
                continue
            yield Violation(
                "TC201",
                f"rank {src} recv (tag {r.get('mtag')}, clk "
                f"{r.get('step')}) names span {r['from_span']:#x} but no "
                "journaled send carries that span — a message from "
                "outside the run",
            )
            continue
        if s.get("dst") != src:
            yield Violation(
                "TC201",
                f"send span {s['span']:#x} was addressed to rank "
                f"{s.get('dst')} but was received on rank {src}",
            )
        if r.get("src", -1) >= 0 and merge._rec_rank(s) != r["src"]:
            yield Violation(
                "TC201",
                f"rank {src} recv attributes span {s['span']:#x} to "
                f"rank {r['src']} but rank {merge._rec_rank(s)} sent it",
            )
        if s.get("mtag") != r.get("mtag"):
            yield Violation(
                "TC201",
                f"span {s['span']:#x} sent with tag {s.get('mtag')} but "
                f"received with tag {r.get('mtag')}",
            )
        if (
            isinstance(r.get("step"), int)
            and isinstance(s.get("step"), int)
            and r["step"] <= s["step"]
        ):
            yield Violation(
                "TC201",
                f"Lamport order inverted for span {s['span']:#x}: send "
                f"clk {s['step']} >= recv clk {r['step']} (the receiver "
                "never observed the sender's clock)",
            )


def _tc202_conservation(
    records, faults, sem=None, churned: frozenset = frozenset()
) -> Iterable[Violation]:
    sends_ok: dict = {}
    recvs: dict = {}
    for r in records:
        if r["ev"] in ("send", "isend"):
            if "err" in r:
                continue  # the transport raised: the message never left
            key = (merge._rec_rank(r), r.get("dst"), r.get("mtag"))
            sends_ok[key] = sends_ok.get(key, 0) + 1
        elif r["ev"] == "recv" and r.get("src", -1) >= 0:
            key = (r["src"], merge._rec_rank(r), r.get("mtag"))
            recvs[key] = recvs.get(key, 0) + 1
    dup: dict = {}
    lost: dict = {}
    for f in faults:
        key = (f.get("src"), f.get("dst"), f.get("tag"))
        kind = f.get("kind")
        if kind in _DUP_KINDS:
            dup[key] = dup.get(key, 0) + 1
        elif kind not in _DELIVERED_KINDS:
            # drop / blackhole / reset / kill: the copy never arrives
            lost[key] = lost.get(key, 0) + 1
    # A duplicated *request* makes the responder send one extra reply;
    # when the duplicate lands after the requester's last round, that
    # reply sits undrained in the socket at process exit. License the
    # deficit on the reply stream by the duplication faults journaled
    # on the reverse request stream (an upper bound: drained extras
    # show up as stale-attempt recvs and need no allowance).
    orphan: dict = {}
    if sem is not None and sem.reply_tag is not None:
        for (fsrc, fdst, ftag), n in dup.items():
            if ftag == sem.request_tag:
                rkey = (fdst, fsrc, sem.reply_tag)
                orphan[rkey] = orphan.get(rkey, 0) + n
    for key in sorted(set(sends_ok) | set(recvs), key=str):
        src, dst, tag = key
        if src in churned or dst in churned:
            # a killed endpoint loses buffered journal records AND
            # in-flight messages with no fault-log entry to blame —
            # this stream's counts cannot be expected to balance
            continue
        ns, nr = sends_ok.get(key, 0), recvs.get(key, 0)
        hi = ns + dup.get(key, 0)
        lo = max(0, ns - lost.get(key, 0) - orphan.get(key, 0))
        name = merge._tag_name(tag)
        if nr > hi:
            yield Violation(
                "TC202",
                f"stream {src}->{dst} {name}: {nr} recv(s) but only "
                f"{ns} err-free send(s) + {dup.get(key, 0)} duplication "
                "fault(s) — phantom deliveries",
            )
        elif nr < lo:
            extra = (
                f" + {orphan[key]} dup-request orphan(s)"
                if orphan.get(key) else ""
            )
            yield Violation(
                "TC202",
                f"stream {src}->{dst} {name}: {nr} recv(s) for {ns} "
                f"err-free send(s) with only {lost.get(key, 0)} "
                f"loss fault(s){extra} to blame — messages vanished",
            )


def _tc203_roles(records, roles) -> Iterable[Violation]:
    if not roles:
        return
    alphabet = set()
    for rm in roles.values():
        alphabet |= rm.sent_tags
    sent_by_rank: dict = {}
    for r in records:
        if r["ev"] in ("send", "isend") and r.get("mtag") is not None:
            sent_by_rank.setdefault(merge._rec_rank(r), set()).add(
                r["mtag"]
            )
    for rank in sorted(sent_by_rank):
        tags = sent_by_rank[rank]
        unknown = tags - alphabet
        if unknown:
            yield Violation(
                "TC203",
                f"rank {rank} sent tag(s) "
                f"{sorted(unknown)} that no extracted role ever sends — "
                "outside the protocol alphabet",
            )
            tags = tags - unknown
        if tags and not any(
            tags <= rm.sent_tags for rm in roles.values()
        ):
            parts = {
                name: sorted(tags & rm.sent_tags)
                for name, rm in sorted(roles.items())
                if tags & rm.sent_tags
            }
            yield Violation(
                "TC203",
                f"rank {rank} sent {sorted(tags)} — an alphabet no "
                f"single role owns (split across {parts}); one rank is "
                "playing several protocol roles at once",
            )


def _tc204_version_monotonic(records) -> Iterable[Violation]:
    # journal-file order IS per-rank real-time order (the journal lock
    # stamps t monotonically; a respawned process appends to the same
    # file), so a simple last-seen scan suffices. Ordering is (gen,
    # version) lexicographic: a restored server's counter may step back
    # across a gen bump (it resumed from its last snapshot — licensed),
    # never within one generation and never to an earlier generation.
    last: dict = {}
    for r in records:
        if r["ev"] != "param_version":
            continue
        v = r.get("version")
        if not isinstance(v, int):
            continue
        g = r.get("gen", 0)
        if not isinstance(g, int):
            g = 0
        rank = merge._rec_rank(r)
        prev = last.get(rank)
        if prev is not None and (g, v) < prev:
            pg, pv = prev
            yield Violation(
                "TC204",
                f"server rank {rank} PARAM reply carries version {v} "
                f"(gen {g}) after already replying with {pv} (gen {pg}) "
                "— the center version counter went backwards",
            )
        last[rank] = max((g, v), prev) if prev is not None else (g, v)


def check_conformance(
    obs_dir: str,
    project,
    faults_path: Optional[str] = None,
    elastic: Optional[bool] = None,
) -> ConformanceReport:
    """Audit one run directory against the protocol extracted from
    ``project`` (a :class:`mpit_tpu.analysis.lint.Project` over the
    package that implements the roles).

    ``elastic``: ``None`` (default) auto-detects from the supervisor's
    ``membership.jsonl``; ``False`` forces strict mode even when the
    file shows churn; ``True`` only matters as documentation — with no
    membership records there is nothing to license, so it is strict
    anyway (licensing is always scoped to *specific* churned ranks,
    never a blanket waiver)."""
    paths, records, faults = _load(obs_dir, faults_path)
    membership = load_membership(obs_dir) if elastic is not False else []
    churned = churned_ranks(membership)
    truncated = truncated_ranks(records)
    licensed = churned | truncated
    roles = project.roles
    sem = protocol.extract_semantics(project)
    violations = list(_tc201_causality(records, licensed))
    violations.extend(_tc202_conservation(records, faults, sem, licensed))
    violations.extend(_tc203_roles(records, roles))
    violations.extend(_tc204_version_monotonic(records))
    return ConformanceReport(
        journals=paths,
        events=len(records),
        sends=sum(1 for r in records if r["ev"] in ("send", "isend")),
        recvs=sum(1 for r in records if r["ev"] == "recv"),
        faults=len(faults),
        violations=violations,
        churned=sorted(churned),
        truncated=sorted(truncated),
    )
