"""Streaming SLO aggregation over serving journals, and gate files.

Reads the request-lifecycle events ``models/serving.py`` journals
(``req_enqueue``/``req_admit``/``req_first_token``/``req_finish``/
``req_cancel`` plus ``prefill``/``segment``/``serve_fault``/
``journal_cap``) and reduces them to the serving scorecard: TTFT, TPOT,
e2e percentiles, goodput, queue depth and batch occupancy over time.

Constant memory: latencies go into geometric histograms (base 1.1 on
microseconds → percentiles within ~10% quantization at any volume, the
``_lat_bucket`` idea from obs.merge carried further), and per-request
state is held only between enqueue and finish — a journal of millions
of requests aggregates in O(in-flight + buckets).

Gate files (``obs slo <dir> --gate slo.json``) are flat JSON objects of
ceiling/floor keys — ``ttft_p99_ms: 250`` means "p99 TTFT must be at
most 250ms". Unknown keys are an error, not a silent pass: a typo'd
gate must fail loudly rather than wave every build through.
"""

from __future__ import annotations

import json
import math
import re
from typing import Optional

# base-1.1 geometric buckets on microseconds: bucket b covers
# (1.1^(b-1), 1.1^b] µs, so any reported percentile is within one 10%
# step of the true value regardless of how many samples were folded in
_BASE = 1.1
_LOG_BASE = math.log(_BASE)


def _bucket(seconds: float) -> int:
    us = seconds * 1e6
    if us <= 1.0:
        return 0
    return int(math.ceil(math.log(us) / _LOG_BASE))


def _bucket_ms(b: int) -> float:
    return _BASE ** b / 1e3


class _Hist:
    """Geometric latency histogram: O(log range) buckets, exact count
    and mean, percentiles to ~10%."""

    __slots__ = ("counts", "total", "sum_s")

    def __init__(self):
        self.counts: dict = {}
        self.total = 0
        self.sum_s = 0.0

    def add(self, seconds: float) -> None:
        b = _bucket(seconds)
        self.counts[b] = self.counts.get(b, 0) + 1
        self.total += 1
        self.sum_s += seconds

    def percentile_ms(self, q: float) -> Optional[float]:
        if self.total == 0:
            return None
        need = q * self.total
        seen = 0
        for b in sorted(self.counts):
            seen += self.counts[b]
            if seen >= need:
                return _bucket_ms(b)
        return _bucket_ms(max(self.counts))

    def summary(self) -> dict:
        if self.total == 0:
            return {"count": 0}
        return {
            "count": self.total,
            "mean_ms": round(self.sum_s / self.total * 1e3, 3),
            "p50_ms": round(self.percentile_ms(0.50), 3),
            "p90_ms": round(self.percentile_ms(0.90), 3),
            "p99_ms": round(self.percentile_ms(0.99), 3),
        }


class SLOAggregator:
    """Fold journal records (dicts) into the serving scorecard.

    Feed records in any order across files; within one rank's journal
    the serving loop is single-threaded so lifecycle order holds.
    ``default_slo_ms`` applies to requests enqueued without ``slo_ms``
    (None = such requests meet their SLO vacuously when they finish)."""

    def __init__(self, default_slo_ms: Optional[float] = None):
        self.default_slo_ms = default_slo_ms
        # rid -> [t_enqueue, t_first_token|None, slo_ms|None]
        self._open: dict = {}
        self.ttft = _Hist()
        self.tpot = _Hist()
        self.e2e = _Hist()
        self.submitted = 0
        self.finished = 0
        self.cancelled = 0
        self.slo_met = 0
        self.tokens = 0
        self.finish_reasons: dict = {}
        self.faults: dict = {}
        self.dropped_records = 0
        # time-weighted queue depth / occupancy from segment events
        self.segments = 0
        self.spec_segments = 0
        self._seg_time = 0.0
        self._depth_time = 0.0   # ∫ waiting dt over segment time
        self._occ_time = 0.0     # ∫ occupied dt
        self._slot_time = 0.0    # ∫ nslots dt
        self.max_queue_depth = 0
        self._t_min: Optional[float] = None
        self._t_max: Optional[float] = None

    def observe(self, rec: dict) -> None:
        ev = rec.get("ev")
        if ev is None:
            return
        t = rec.get("t")
        if t is not None:
            self._t_min = t if self._t_min is None else min(self._t_min, t)
            self._t_max = t if self._t_max is None else max(self._t_max, t)
        if ev == "req_enqueue":
            self.submitted += 1
            self._open[rec["rid"]] = [
                t, None, rec.get("slo_ms", self.default_slo_ms),
            ]
        elif ev == "req_first_token":
            st = self._open.get(rec["rid"])
            if st is not None and st[1] is None:
                st[1] = t
                if st[0] is not None and t is not None:
                    self.ttft.add(t - st[0])
        elif ev == "req_finish":
            st = self._open.pop(rec["rid"], None)
            self.finished += 1
            gen = rec.get("gen", 0)
            self.tokens += gen
            reason = rec.get("reason", "?")
            self.finish_reasons[reason] = (
                self.finish_reasons.get(reason, 0) + 1
            )
            if st is None or st[0] is None or t is None:
                return
            e2e_s = t - st[0]
            self.e2e.add(e2e_s)
            if st[1] is not None and gen > 1:
                self.tpot.add((t - st[1]) / (gen - 1))
            if st[2] is None or e2e_s * 1e3 <= st[2]:
                self.slo_met += 1
        elif ev == "req_cancel":
            self._open.pop(rec["rid"], None)
            self.cancelled += 1
            self.tokens += rec.get("gen", 0)
        elif ev == "segment":
            self.segments += 1
            if rec.get("spec"):
                self.spec_segments += 1
            dur = rec.get("dur", 0.0)
            waiting = rec.get("waiting", 0)
            self._seg_time += dur
            self._depth_time += waiting * dur
            self._occ_time += rec.get("occupied", 0) * dur
            self._slot_time += rec.get("nslots", 0) * dur
            self.max_queue_depth = max(self.max_queue_depth, waiting)
        elif ev == "serve_fault":
            kind = rec.get("kind", "?")
            self.faults[kind] = self.faults.get(kind, 0) + 1
        elif ev == "journal_cap":
            self.dropped_records += rec.get("dropped_records", 0)

    def report(self) -> dict:
        unfinished = len(self._open)
        denom = self.submitted - self.cancelled
        duration_s = (
            (self._t_max - self._t_min)
            if self._t_min is not None and self._t_max is not None
            else 0.0
        )
        return {
            "requests": {
                "submitted": self.submitted,
                "finished": self.finished,
                "cancelled": self.cancelled,
                "unfinished": unfinished,
            },
            "finish_reasons": dict(sorted(self.finish_reasons.items())),
            "ttft": self.ttft.summary(),
            "tpot": self.tpot.summary(),
            "e2e": self.e2e.summary(),
            # of the requests the client still wanted, the fraction that
            # finished within SLO — unfinished (killed/abandoned) count
            # against, so a crashed run cannot score well
            "goodput": (
                round(self.slo_met / denom, 4) if denom > 0 else None
            ),
            "queue_depth": {
                "time_mean": (
                    round(self._depth_time / self._seg_time, 2)
                    if self._seg_time > 0 else None
                ),
                "max": self.max_queue_depth,
            },
            "occupancy": (
                round(self._occ_time / self._slot_time, 4)
                if self._slot_time > 0 else None
            ),
            "segments": self.segments,
            "spec_segments": self.spec_segments,
            "tokens": self.tokens,
            "duration_s": round(duration_s, 4),
            "tokens_per_sec": (
                round(self.tokens / duration_s, 1)
                if duration_s > 0 else None
            ),
            "faults": dict(sorted(self.faults.items())),
            "dropped_records": self.dropped_records,
        }


def _stream(paths, agg: SLOAggregator) -> SLOAggregator:
    """Feed journal files into ``agg``. Unparseable lines are skipped
    (a crashed writer's torn tail must not take the postmortem down
    with it)."""
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                agg.observe(rec)
    return agg


def aggregate_paths(
    paths, default_slo_ms: Optional[float] = None
) -> dict:
    """Stream journal files through one aggregator; returns the report."""
    return _stream(
        paths, SLOAggregator(default_slo_ms=default_slo_ms)
    ).report()


def pooled_latencies(groups, names=("ttft", "tpot", "e2e")) -> dict:
    """Latency summaries pooled across journal *groups* whose rid
    spaces collide — one group per serving replica (each replica's
    ``Server`` numbers requests locally, so rid 0 in two replica
    journals is two different requests and they must never share one
    aggregator). Each group streams through its own
    :class:`SLOAggregator`; the geometric histograms then merge exactly
    (bucket counts add). Returns ``{name: summary}`` in the same shape
    as the per-histogram ``summary()`` of :func:`aggregate_paths`."""
    pooled = {name: _Hist() for name in names}
    for paths in groups:
        agg = _stream(list(paths), SLOAggregator())
        for name in names:
            h: _Hist = getattr(agg, name)
            dst = pooled[name]
            for b, c in h.counts.items():
                dst.counts[b] = dst.counts.get(b, 0) + c
            dst.total += h.total
            dst.sum_s += h.sum_s
    return {name: hist.summary() for name, hist in pooled.items()}


# gate keys: latency ceilings in ms, plus run-shape floors/ceilings
_LAT_KEY = re.compile(r"^(ttft|tpot|e2e)_p(50|90|99)_ms$")
_OTHER_KEYS = frozenset(
    ("goodput_min", "min_finished", "max_unfinished",
     "max_dropped_records")
)


def validate_gate(gate: dict) -> None:
    if not isinstance(gate, dict):
        raise ValueError("gate must be a JSON object")
    for k, v in gate.items():
        if not (_LAT_KEY.match(k) or k in _OTHER_KEYS):
            raise ValueError(
                f"unknown gate key {k!r} (latency gates look like "
                "ttft_p99_ms; others: " + ", ".join(sorted(_OTHER_KEYS))
                + ")"
            )
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"gate {k!r} must be a number, got {v!r}")


def load_gate(path: str) -> dict:
    with open(path) as f:
        gate = json.load(f)
    validate_gate(gate)
    return gate


def evaluate_gate(report: dict, gate: dict) -> list:
    """Violation strings (empty = pass). A gated percentile that the
    run produced no samples for is itself a violation — a gate must not
    pass because the thing it bounds never happened."""
    out = []
    for k, limit in gate.items():
        m = _LAT_KEY.match(k)
        if m:
            metric, pct = m.group(1), "p" + m.group(2) + "_ms"
            got = report.get(metric, {}).get(pct)
            if got is None:
                out.append(f"{k}: no samples (gate needs <= {limit})")
            elif got > limit:
                out.append(f"{k}: {got} > {limit}")
        elif k == "goodput_min":
            got = report.get("goodput")
            if got is None:
                out.append(f"goodput_min: no eligible requests "
                           f"(gate needs >= {limit})")
            elif got < limit:
                out.append(f"goodput_min: {got} < {limit}")
        elif k == "min_finished":
            got = report["requests"]["finished"]
            if got < limit:
                out.append(f"min_finished: {got} < {limit}")
        elif k == "max_unfinished":
            got = report["requests"]["unfinished"]
            if got > limit:
                out.append(f"max_unfinished: {got} > {limit}")
        elif k == "max_dropped_records":
            got = report.get("dropped_records", 0)
            if got > limit:
                out.append(f"max_dropped_records: {got} > {limit}")
    return out


def format_report(report: dict) -> str:
    """Human-readable scorecard (the ``obs slo`` default output)."""
    r = report["requests"]
    lines = [
        f"requests: {r['submitted']} submitted, {r['finished']} "
        f"finished, {r['cancelled']} cancelled, "
        f"{r['unfinished']} unfinished",
    ]
    for name in ("ttft", "tpot", "e2e"):
        s = report[name]
        if s.get("count"):
            lines.append(
                f"{name:>4}: p50 {s['p50_ms']:.3f}ms  "
                f"p90 {s['p90_ms']:.3f}ms  p99 {s['p99_ms']:.3f}ms  "
                f"(mean {s['mean_ms']:.3f}ms, n={s['count']})"
            )
        else:
            lines.append(f"{name:>4}: no samples")
    gp = report["goodput"]
    lines.append(
        "goodput: " + (f"{gp:.4f}" if gp is not None else "n/a")
    )
    qd = report["queue_depth"]
    qmean = qd["time_mean"]
    lines.append(
        "queue depth: "
        + (f"{qmean} time-mean" if qmean is not None else "n/a")
        + f", {qd['max']} max"
    )
    occ = report["occupancy"]
    lines.append(
        "occupancy: " + (f"{occ:.4f}" if occ is not None else "n/a")
        + f" over {report['segments']} segments"
    )
    tps = report["tokens_per_sec"]
    lines.append(
        f"tokens: {report['tokens']} in {report['duration_s']}s"
        + (f" ({tps} tok/s)" if tps is not None else "")
    )
    if report["faults"]:
        lines.append(
            "faults: " + ", ".join(
                f"{k}={v}" for k, v in report["faults"].items()
            )
        )
    if report["dropped_records"]:
        lines.append(
            f"WARNING: journal dropped {report['dropped_records']} "
            "records (MPIT_OBS_MAX_RECORDS cap) — stats above are "
            "truncated"
        )
    return "\n".join(lines)
