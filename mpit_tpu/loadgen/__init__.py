"""Open-loop load generation and SLO accounting for the serving stack.

The measurement half of "serving under traffic": :mod:`workload` draws
a seeded request schedule (Poisson arrivals, mixed length buckets,
cancellations), :mod:`harness` replays it against a live
``Server``/``RNNServer`` (optionally under :mod:`chaos` faults), the
server's obs journal records every request's lifecycle, and :mod:`slo`
reduces journals to TTFT/TPOT/e2e percentiles + goodput — gated via
``python -m mpit_tpu.obs slo <dir> --gate slo.json``. docs/SERVING.md
has the walkthrough.
"""

from mpit_tpu.loadgen.chaos import ServeChaos
from mpit_tpu.loadgen.harness import LoadHarness, LoadReport
from mpit_tpu.loadgen.slo import (
    SLOAggregator,
    aggregate_paths,
    evaluate_gate,
    format_report,
    load_gate,
    pooled_latencies,
    validate_gate,
)
from mpit_tpu.loadgen.workload import LoadSpec, Request, make_workload

__all__ = [
    "LoadSpec",
    "Request",
    "make_workload",
    "ServeChaos",
    "LoadHarness",
    "LoadReport",
    "SLOAggregator",
    "aggregate_paths",
    "evaluate_gate",
    "format_report",
    "load_gate",
    "pooled_latencies",
    "validate_gate",
]
