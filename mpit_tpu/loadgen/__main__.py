"""Seeded load run against a tiny in-process server, journaled for
``obs slo``.

    python -m mpit_tpu.loadgen --out /tmp/serve_obs --seed 3 \\
        --requests 48 --rate 200 --cancel-prob 0.1

builds a smoke-sized model (transformer by default, ``--rnn`` for the
carry-decode family), drives the open-loop harness against it with the
server journaling every request lifecycle into ``--out``, and prints
one JSON report line (the same reduction ``obs slo`` computes). Chain::

    python -m mpit_tpu.obs slo /tmp/serve_obs --gate scripts/slo_smoke.json

Every knob that shapes the run is on the command line and the run is a
pure function of them — rerunning a failed soak's line replays it.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.loadgen",
        description="seeded open-loop load run against an in-process "
        "server, journaled for `python -m mpit_tpu.obs slo`",
    )
    p.add_argument("--out", required=True,
                   help="journal directory (created if missing)")
    p.add_argument("--seed", type=int, default=0,
                   help="workload + chaos seed (default 0)")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--rate", type=float, default=200.0,
                   help="Poisson arrival rate, req/s (default 200)")
    p.add_argument("--cancel-prob", type=float, default=0.0)
    p.add_argument("--rnn", action="store_true",
                   help="serve the LSTM family (RNNServer) instead of "
                   "the transformer")
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--segment", type=int, default=8)
    p.add_argument("--chaos-delay-p", type=float, default=0.0,
                   help="per-boundary stall probability (seeded)")
    p.add_argument("--chaos-delay-s", type=float, default=0.02)
    p.add_argument("--kill-after", type=int, default=None,
                   help="kill the server at this boundary (seeded soak "
                   "crash drill)")
    p.add_argument("--max-records", type=int, default=None,
                   help="journal record cap (journal_cap footer counts "
                   "the drops)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the unjournaled warmup drain; first-run "
                   "XLA compiles then land in the measured TTFTs")
    p.add_argument("--live", action="store_true",
                   help="arm the live telemetry plane: snapshots in "
                   "<out>/live, watch with `python -m mpit_tpu.obs "
                   "live <out>`")
    p.add_argument("--live-interval", type=float, default=0.25,
                   help="live snapshot export interval, seconds "
                   "(default 0.25 — smoke runs are short)")
    return p


def main(argv=None) -> int:
    ns = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from mpit_tpu.loadgen import (
        LoadHarness, LoadSpec, ServeChaos, aggregate_paths,
        make_workload,
    )
    from mpit_tpu.obs.core import ObsConfig

    vocab = 17
    if ns.rnn:
        from mpit_tpu.models import RNNServer
        from mpit_tpu.models.lstm import LSTMLM

        model = LSTMLM(
            vocab_size=vocab, embed_dim=12, hidden=16, num_layers=2,
            compute_dtype=jnp.float32,
        )
        server_cls, max_len = RNNServer, None
    else:
        from mpit_tpu.models import Server
        from mpit_tpu.models.transformer import TransformerLM

        model = TransformerLM(
            vocab_size=vocab, num_layers=2, d_model=32, num_heads=4,
            max_len=64, compute_dtype=jnp.float32,
        )
        server_cls, max_len = Server, 64
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    spec = LoadSpec(
        requests=ns.requests, rate=ns.rate, seed=ns.seed,
        cancel_prob=ns.cancel_prob,
    )
    work = make_workload(spec, vocab, max_len=max_len)

    if not ns.no_warmup:
        # compile every bucket shape outside the journal, so measured
        # TTFT is scheduling + compute, not XLA compile time
        warm = server_cls(
            model, params, max_batch=ns.max_batch, segment=ns.segment,
        )
        for r in work:
            warm.submit(list(r.prompt), r.max_new)
        warm.drain()

    srv = server_cls(
        model, params, max_batch=ns.max_batch, segment=ns.segment,
        obs=ObsConfig(
            dir=ns.out, max_records=ns.max_records,
            live=ns.live, live_interval=ns.live_interval,
        ),
    )
    chaos = None
    if ns.chaos_delay_p > 0.0 or ns.kill_after is not None:
        chaos = ServeChaos(
            seed=ns.seed, delay_p=ns.chaos_delay_p,
            delay_s=ns.chaos_delay_s, kill_after=ns.kill_after,
        )
    harness = LoadHarness(srv, work, chaos=chaos)
    rep = harness.run()

    import glob
    import os

    report = aggregate_paths(
        sorted(glob.glob(os.path.join(ns.out, "obs_rank*.jsonl")))
    )
    report["client"] = {
        "submitted": rep.submitted,
        "cancelled": rep.cancelled,
        "killed": rep.killed,
        "boundaries": rep.boundaries,
        "wall_s": round(rep.wall_s, 4),
        "max_submit_lateness_s": round(rep.max_submit_lateness_s, 6),
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
