"""Seeded chaos for the serving loop: boundary delays and a mid-run kill.

The serving analogue of :class:`mpit_tpu.transport.chaos.ChaosTransport`
with the same determinism contract — every fault is a pure function of
``(seed, boundary_index)`` via the shared :func:`_mix` hash, never of
wall-clock or scheduling jitter, so a failing soak seed replays the
identical fault schedule. The harness applies faults at scheduling
boundaries (the only points the host controls anyway):

- ``delay``: sleep before the boundary's segment — a stalled host /
  preempted core / GC pause. Rare large delays are the p99 story: a
  request unlucky enough to span a delayed boundary eats the whole
  stall, the median request never sees one (pinned in
  tests/test_loadgen.py: p99 moves, p50 stays).
- ``kill``: the server dies at boundary N — in-flight and queued
  requests are abandoned, which ``obs slo`` reports as ``unfinished``
  (goodput counts them against, a killed run can't hide its losses).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from mpit_tpu.transport.chaos import _mix

# domain separator: serving draws must not collide with wire-chaos
# draws made from the same user seed
_SERVE_STREAM = 0x5E12E


@dataclasses.dataclass(frozen=True)
class ServeChaos:
    """One frozen fault schedule for a load run.

    ``delay_p``: per-boundary probability of a stall; ``delay_s``: its
    magnitude (jittered ±50%, seeded); ``kill_after``: boundary index at
    which the server dies (None = never)."""

    seed: int = 0
    delay_p: float = 0.0
    delay_s: float = 0.02
    kill_after: Optional[int] = None

    def __post_init__(self):
        if not (0.0 <= self.delay_p <= 1.0):
            raise ValueError("delay_p must be in [0, 1]")
        if self.delay_s < 0:
            raise ValueError("delay_s must be >= 0")
        if self.kill_after is not None and self.kill_after < 0:
            raise ValueError("kill_after must be >= 0")

    def draw(self, boundary: int):
        """The fault for scheduling boundary ``boundary``:
        ``("kill", 0.0)``, ``("delay", seconds)``, or None. Stateless —
        replaying any boundary yields the identical draw."""
        if self.kill_after is not None and boundary >= self.kill_after:
            return ("kill", 0.0)
        if self.delay_p <= 0.0 or self.delay_s <= 0.0:
            return None
        rng = random.Random(_mix(self.seed, _SERVE_STREAM, boundary))
        if rng.random() >= self.delay_p:
            return None
        return ("delay", self.delay_s * (0.5 + rng.random()))
