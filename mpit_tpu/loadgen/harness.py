"""In-process open-loop load harness for ``Server``/``RNNServer``.

Drives a scheduled workload (:func:`~mpit_tpu.loadgen.workload.
make_workload`) against a live server on one thread: at every loop turn
it submits the arrivals that have come due, fires due cancellations,
optionally applies the boundary's chaos fault, and runs one scheduling
step. Open-loop means arrivals never wait for capacity — under overload
the server's queue grows and TTFT/e2e stretch, which is the measurement.

The per-request record is the SERVER's obs journal (construct the
server with ``obs=ObsConfig(dir=...)``); the harness adds only its
chaos faults (``serve_fault`` via ``Server.obs_event``) and returns a
client-side :class:`LoadReport`. One caveat the journal carries: the
loop is single-threaded, so an arrival due mid-segment is submitted at
the next boundary — ``max_submit_lateness_s`` bounds how much TTFT
undercounts that way (a segment's wall-clock at most; keep segments
small when measuring tight SLOs, docs/SERVING.md).
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Optional

from mpit_tpu.loadgen.chaos import ServeChaos
from mpit_tpu.loadgen.workload import Request
from mpit_tpu.obs.live import (
    M_LOAD_LATENESS_S,
    M_LOAD_PENDING,
    live_registry,
)


@dataclasses.dataclass
class LoadReport:
    """Client-side outcome of one harness run. ``results`` maps rid →
    full token list (prompt included — the Server convention);
    ``requests`` maps rid back to its scheduled :class:`Request`."""

    results: dict
    requests: dict
    submitted: int
    cancelled: int
    killed: bool
    boundaries: int
    wall_s: float
    max_submit_lateness_s: float


class LoadHarness:
    """Run one workload against one server.

    ``chaos``: optional :class:`~mpit_tpu.loadgen.chaos.ServeChaos`
    applied per boundary. ``idle_sleep``: poll granularity while waiting
    for the next arrival with an empty server (bounded busy-wait)."""

    def __init__(
        self,
        server,
        requests: list,
        chaos: Optional[ServeChaos] = None,
        idle_sleep: float = 0.001,
    ):
        self.server = server
        self.requests = sorted(requests, key=lambda r: r.arrival_s)
        self.chaos = chaos
        self.idle_sleep = idle_sleep

    def run(self) -> LoadReport:
        srv = self.server
        reqs = self.requests
        # harness-side live gauges through the server's registry (the
        # no-op NULL_REGISTRY unless ObsConfig.live armed the server):
        # the client's view — pending load and submit lateness — rides
        # the same snapshots the server's lifecycle counters do
        reg = live_registry(srv)
        t0 = time.perf_counter()
        i = 0
        cancels: list = []  # (due_s, rid) min-heap
        results: dict = {}
        by_rid: dict = {}
        cancelled = 0
        killed = False
        boundary = 0
        max_late = 0.0
        while True:
            now = time.perf_counter() - t0
            while i < len(reqs) and reqs[i].arrival_s <= now:
                r = reqs[i]
                r.rid = srv.submit(
                    list(r.prompt), r.max_new,
                    temperature=r.temperature, top_p=r.top_p,
                    slo_ms=r.slo_ms,
                )
                by_rid[r.rid] = r
                max_late = max(max_late, now - r.arrival_s)
                if r.cancel_after_s is not None:
                    heapq.heappush(
                        cancels, (now + r.cancel_after_s, r.rid)
                    )
                i += 1
            while cancels and cancels[0][0] <= now:
                _, rid = heapq.heappop(cancels)
                if srv.cancel(rid):  # False: already finished — keep it
                    cancelled += 1
            results.update(srv.results())
            if srv.pending == 0:
                if i >= len(reqs):
                    break
                gap = reqs[i].arrival_s - now
                if gap > 0:
                    time.sleep(min(self.idle_sleep, gap))
                continue
            if self.chaos is not None:
                fault = self.chaos.draw(boundary)
                if fault is not None:
                    kind, delay = fault
                    srv.obs_event(
                        "serve_fault", kind=kind, boundary=boundary,
                        **({"delay": round(delay, 6)} if delay else {}),
                    )
                    if kind == "kill":
                        killed = True
                        break
                    time.sleep(delay)
            srv.step()
            boundary += 1
            reg.set_gauge(M_LOAD_PENDING, srv.pending)
            reg.set_gauge(M_LOAD_LATENESS_S, max_late)
        results.update(srv.results())
        srv.close()
        return LoadReport(
            results=results,
            requests=by_rid,
            submitted=i,
            cancelled=cancelled,
            killed=killed,
            boundaries=boundary,
            wall_s=time.perf_counter() - t0,
            max_submit_lateness_s=max_late,
        )
