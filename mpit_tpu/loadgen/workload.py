"""Open-loop workload generation: seeded, replayable request schedules.

The open-loop discipline (the serving-benchmark standard): requests
arrive at times drawn ONCE from a Poisson process and do not slow down
when the server falls behind — queueing delay shows up in TTFT instead
of silently throttling the offered load, which is exactly the failure
mode a closed loop hides.

Everything is derived from ``LoadSpec.seed`` through one
``random.Random`` stream: same spec → token-identical schedule (arrival
times, prompts, budgets, sampling overrides, cancellations), the replay
contract ``transport.chaos`` established for faults applied to traffic.
Stdlib-only — the schedule can be generated (and asserted on) without
jax.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

# (lo, hi, weight): lengths drawn uniformly from [lo, hi), buckets drawn
# by weight — the mixed prompt/output regimes of real traffic (short
# chat, long context, long generation) in one schedule
Buckets = tuple


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One frozen spec per run (the ChaosConfig idiom).

    ``rate`` is the Poisson arrival rate in requests/second — the
    offered load, independent of service capacity. Per-request e2e SLOs
    scale with the budget (``slo_base_ms + slo_per_token_ms * max_new``)
    so a long generation is not penalized for being long; goodput then
    measures scheduling, not workload mix. ``temperatures``/``top_ps``
    are per-request override choices (empty = server defaults; only
    valid against a sampling server). ``cancel_prob`` of the requests
    abandon mid-stream, ``cancel_after_s`` (±50%) after arrival."""

    requests: int = 32
    rate: float = 100.0
    seed: int = 0
    prompt_buckets: Buckets = ((1, 8, 0.6), (8, 24, 0.3), (24, 40, 0.1))
    output_buckets: Buckets = ((2, 8, 0.6), (8, 20, 0.4))
    cancel_prob: float = 0.0
    cancel_after_s: float = 0.05
    temperatures: tuple = ()
    top_ps: tuple = ()
    slo_base_ms: float = 1000.0
    slo_per_token_ms: float = 100.0

    def __post_init__(self):
        if self.requests < 1:
            raise ValueError("requests must be >= 1")
        if self.rate <= 0:
            raise ValueError("rate must be > 0")
        if not (0.0 <= self.cancel_prob <= 1.0):
            raise ValueError("cancel_prob must be in [0, 1]")
        for name, buckets in (
            ("prompt_buckets", self.prompt_buckets),
            ("output_buckets", self.output_buckets),
        ):
            if not buckets:
                raise ValueError(f"{name} must be non-empty")
            for lo, hi, w in buckets:
                if lo < 1 or hi <= lo or w <= 0:
                    raise ValueError(
                        f"{name} entry ({lo}, {hi}, {w}) needs "
                        "1 <= lo < hi and weight > 0"
                    )


@dataclasses.dataclass
class Request:
    """One scheduled request. ``rid`` is filled by the harness at submit
    time — the join key between the schedule and the server's journal."""

    arrival_s: float
    prompt: tuple
    max_new: int
    slo_ms: float
    cancel_after_s: Optional[float] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    rid: Optional[int] = None


def _pick_len(rng: random.Random, buckets) -> int:
    total = sum(w for _, _, w in buckets)
    x = rng.random() * total
    for lo, hi, w in buckets:
        x -= w
        if x <= 0:
            return rng.randrange(lo, hi)
    lo, hi, _ = buckets[-1]
    return rng.randrange(lo, hi)


def make_workload(
    spec: LoadSpec, vocab_size: int, max_len: Optional[int] = None
) -> list[Request]:
    """The schedule: ``spec.requests`` Requests in arrival order.

    ``max_len`` is the server's effective horizon (``model.max_len``
    minus any shared prefix; None for horizon-free RNNs): drawn lengths
    are clamped so ``prompt + max_new <= max_len`` with at least one
    token of each — a spec can oversubscribe the horizon without
    producing requests ``submit`` would reject. Token values are drawn
    from ``[1, vocab_size)`` (0 left out as the conventional pad id).
    """
    if vocab_size < 2:
        raise ValueError("vocab_size must be >= 2")
    rng = random.Random(spec.seed)
    t = 0.0
    out: list[Request] = []
    for _ in range(spec.requests):
        t += rng.expovariate(spec.rate)
        p_len = _pick_len(rng, spec.prompt_buckets)
        m_new = _pick_len(rng, spec.output_buckets)
        if max_len is not None:
            p_len = max(1, min(p_len, max_len - 1))
            m_new = max(1, min(m_new, max_len - p_len))
        # every draw below happens unconditionally so the stream stays
        # aligned across spec knob changes that don't touch it
        cancel_draw = rng.random()
        cancel_jitter = rng.random()
        temp = rng.choice(spec.temperatures) if spec.temperatures else None
        top_p = rng.choice(spec.top_ps) if spec.top_ps else None
        prompt = tuple(
            rng.randrange(1, vocab_size) for _ in range(p_len)
        )
        out.append(Request(
            arrival_s=t,
            prompt=prompt,
            max_new=m_new,
            slo_ms=spec.slo_base_ms + spec.slo_per_token_ms * m_new,
            cancel_after_s=(
                spec.cancel_after_s * (0.5 + cancel_jitter)
                if cancel_draw < spec.cancel_prob else None
            ),
            temperature=temp,
            top_p=top_p,
        ))
    return out
