"""mpirun-equivalent local process launcher.

Reference parity (SURVEY.md §1 launcher row, §3(a)): the reference was
started as ``mpirun -n N th asyncsgd/ptest.lua`` — N OS processes, ranks
discovered via MPI, rank→role split inside the script. This launcher is that
layer for the host-async PS mode:

    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py [script args...]

It allocates one TCP port per rank, exports the world to each child
(``MPIT_RANK``, ``MPIT_WORLD_SIZE``, ``MPIT_TRANSPORT_HOSTS``), and
supervises: first non-zero exit terminates the rest (the do-better over
MPI's hang-on-dead-rank, SURVEY.md §5). Output is line-prefixed with the
rank, mpirun-style. Single-host by design — across hosts you run one
process per host yourself and set ``MPIT_TRANSPORT_HOSTS`` to the real
addresses (same env contract).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _reserve_ports(n: int) -> tuple[list[socket.socket], list[int]]:
    """Reserve n distinct free TCP ports; the RESERVING SOCKETS STAY OPEN.

    The caller closes each one immediately before spawning the rank that
    will bind it — shrinking the steal window (another process grabbing the
    port between reservation and child bind) from the whole launch sequence
    to one process spawn. The child surfaces a clear error if it loses even
    that race (SocketTransport's bind diagnostic)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    except BaseException:
        for s in socks:
            s.close()
        raise
    return socks, ports


def _stream(rank: int, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{rank}] ".encode() + line)
        out.flush()
    pipe.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.launch", description=__doc__
    )
    p.add_argument("-n", "--np", type=int, required=True, dest="n",
                   help="number of processes (ranks)")
    p.add_argument(
        "--jax-distributed", action="store_true",
        help="also bootstrap a jax.distributed world across the ranks "
             "(global device mesh + cross-process XLA collectives), the "
             "multi-host analogue of a CUDA-aware MPI launch",
    )
    p.add_argument("script", help="python script to run in every rank")
    p.add_argument("args", nargs=argparse.REMAINDER,
                   help="arguments passed through to the script")
    ns = p.parse_args(argv)
    if ns.n < 1:
        p.error("-n must be >= 1")

    # chaos knobs are inherited by every rank (env passthrough below):
    # fault injection silently active in a "real" run is a support
    # nightmare, so say it loudly once at launch (docs/ROBUSTNESS.md)
    chaos_env = sorted(k for k in os.environ if k.startswith("MPIT_CHAOS_"))
    if chaos_env:
        print(
            "[launch] CHAOS fault injection active in all ranks: "
            + " ".join(f"{k}={os.environ[k]}" for k in chaos_env),
            file=sys.stderr,
        )
    # same loud-once courtesy for observability: tracing adds a small
    # per-message envelope and journal writes, so a run with it armed
    # should say so (docs/OBSERVABILITY.md)
    obs_env = sorted(k for k in os.environ if k.startswith("MPIT_OBS_"))
    if obs_env:
        print(
            "[launch] OBS tracing/telemetry active in all ranks: "
            + " ".join(f"{k}={os.environ[k]}" for k in obs_env),
            file=sys.stderr,
        )
    # live telemetry gets one more line: unlike journals, it is useful
    # WHILE the run is alive, so print the watch command
    if (
        os.environ.get("MPIT_OBS_LIVE", "0") not in ("", "0")
        and os.environ.get("MPIT_OBS_DIR")
    ):
        print(
            "[launch] LIVE telemetry: snapshots in "
            f"{os.path.join(os.environ['MPIT_OBS_DIR'], 'live')} — watch "
            f"with `python -m mpit_tpu.obs live "
            f"{os.environ['MPIT_OBS_DIR']}`",
            file=sys.stderr,
        )
    # and hung-job forensics: each rank will dump all-thread stacks on a
    # timer (stacks_rank<r>.txt next to the journal, stderr without a dir)
    if os.environ.get("MPIT_OBS_FAULTHANDLER", "0") not in ("", "0"):
        print(
            "[launch] FAULTHANDLER armed in all ranks: periodic "
            "all-thread stack dumps every "
            f"{os.environ['MPIT_OBS_FAULTHANDLER']}"
            " (1 = 300 s default interval)",
            file=sys.stderr,
        )

    # one extra port for the jax.distributed coordinator (rank 0 binds it)
    reserving, ports = _reserve_ports(ns.n + (1 if ns.jax_distributed else 0))
    coord_sock, coord_port = None, None
    if ns.jax_distributed:
        # released right before rank 0 spawns, same as the rank ports —
        # closing it here would open a steal window of the whole launch
        coord_sock, coord_port = reserving.pop(), ports.pop()
    hosts = ",".join(f"127.0.0.1:{port}" for port in ports)

    procs: list[subprocess.Popen] = []
    streams: list[threading.Thread] = []
    try:
        for rank in range(ns.n):
            env = dict(os.environ)
            env["MPIT_RANK"] = str(rank)
            env["MPIT_WORLD_SIZE"] = str(ns.n)
            env["MPIT_TRANSPORT_HOSTS"] = hosts
            if coord_port is not None:
                env["MPIT_DISTRIBUTED"] = "1"
                env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{coord_port}"
            # release this rank's port only now, right before its process
            # exists (and the coordinator port with rank 0, which binds it)
            if rank == 0 and coord_sock is not None:
                coord_sock.close()
            reserving[rank].close()
            proc = subprocess.Popen(
                [sys.executable, ns.script, *ns.args],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
            procs.append(proc)
            t = threading.Thread(
                target=_stream, args=(rank, proc.stdout, sys.stdout.buffer),
                daemon=True,
            )
            t.start()
            streams.append(t)
    except BaseException:
        # a failed spawn mid-loop must not strand reservations (they'd stay
        # bound for the launcher's lifetime) or leave earlier ranks spinning
        # in connect-retry against ports that will never get a listener
        for s in reserving:
            s.close()
        if coord_sock is not None:
            coord_sock.close()
        for proc in procs:
            proc.terminate()
        raise

    rc = 0
    try:
        remaining = set(range(ns.n))
        while remaining:
            for r in sorted(remaining):
                code = procs[r].poll()
                if code is None:
                    continue
                remaining.discard(r)
                if code != 0 and rc == 0:
                    rc = code
                    print(
                        f"[launch] rank {r} exited with {code}; "
                        "terminating the world",
                        file=sys.stderr,
                    )
                    for other in sorted(remaining):
                        procs[other].terminate()
            if remaining:
                try:
                    procs[min(remaining)].wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    pass
    except KeyboardInterrupt:
        for proc in procs:
            proc.send_signal(signal.SIGINT)
        rc = 130
    for proc in procs:
        proc.wait()
    for t in streams:
        t.join(timeout=2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
