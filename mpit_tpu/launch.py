"""mpirun-equivalent local process launcher.

Reference parity (SURVEY.md §1 launcher row, §3(a)): the reference was
started as ``mpirun -n N th asyncsgd/ptest.lua`` — N OS processes, ranks
discovered via MPI, rank→role split inside the script. This launcher is that
layer for the host-async PS mode:

    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py [script args...]

It allocates one TCP port per rank, exports the world to each child
(``MPIT_RANK``, ``MPIT_WORLD_SIZE``, ``MPIT_TRANSPORT_HOSTS``), and
supervises: first non-zero exit terminates the rest (the do-better over
MPI's hang-on-dead-rank, SURVEY.md §5). Output is line-prefixed with the
rank, mpirun-style. Single-host by design — across hosts you run one
process per host yourself and set ``MPIT_TRANSPORT_HOSTS`` to the real
addresses (same env contract).

Elastic supervision (docs/ROBUSTNESS.md): with ``MPIT_ELASTIC_RESPAWN=1``
a rank that dies (crash OR the built-in seeded chaos killer,
``MPIT_ELASTIC_KILL_EVERY_S``) is respawned in place — same rank, same
port (SocketTransport sets SO_REUSEADDR; peers reconnect inside their
connect-retry window) — up to ``MPIT_ELASTIC_MAX_RESPAWNS`` times per
rank, with ``MPIT_RESPAWN_GEN`` exported so the child knows its restart
generation. Every membership transition is journaled to
``$MPIT_OBS_DIR/membership.jsonl`` so trace conformance can tell a
preemption-severed journal from a real protocol violation.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

from mpit_tpu.analysis.runtime import make_lock


def _reserve_ports(n: int) -> tuple[list[socket.socket], list[int]]:
    """Reserve n distinct free TCP ports; the RESERVING SOCKETS STAY OPEN.

    The caller closes each one immediately before spawning the rank that
    will bind it — shrinking the steal window (another process grabbing the
    port between reservation and child bind) from the whole launch sequence
    to one process spawn. The child surfaces a clear error if it loses even
    that race (SocketTransport's bind diagnostic)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    except BaseException:
        for s in socks:
            s.close()
        raise
    return socks, ports


def _stream(rank: int, pipe, out):
    for line in iter(pipe.readline, b""):
        out.write(f"[{rank}] ".encode() + line)
        out.flush()
    pipe.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.launch", description=__doc__
    )
    p.add_argument("-n", "--np", type=int, required=True, dest="n",
                   help="number of processes (ranks)")
    p.add_argument(
        "--jax-distributed", action="store_true",
        help="also bootstrap a jax.distributed world across the ranks "
             "(global device mesh + cross-process XLA collectives), the "
             "multi-host analogue of a CUDA-aware MPI launch",
    )
    p.add_argument("script", help="python script to run in every rank")
    p.add_argument("args", nargs=argparse.REMAINDER,
                   help="arguments passed through to the script")
    ns = p.parse_args(argv)
    if ns.n < 1:
        p.error("-n must be >= 1")

    # chaos knobs are inherited by every rank (env passthrough below):
    # fault injection silently active in a "real" run is a support
    # nightmare, so say it loudly once at launch (docs/ROBUSTNESS.md)
    chaos_env = sorted(k for k in os.environ if k.startswith("MPIT_CHAOS_"))
    if chaos_env:
        print(
            "[launch] CHAOS fault injection active in all ranks: "
            + " ".join(f"{k}={os.environ[k]}" for k in chaos_env),
            file=sys.stderr,
        )
    # same loud-once courtesy for observability: tracing adds a small
    # per-message envelope and journal writes, so a run with it armed
    # should say so (docs/OBSERVABILITY.md)
    obs_env = sorted(k for k in os.environ if k.startswith("MPIT_OBS_"))
    if obs_env:
        print(
            "[launch] OBS tracing/telemetry active in all ranks: "
            + " ".join(f"{k}={os.environ[k]}" for k in obs_env),
            file=sys.stderr,
        )
    # live telemetry gets one more line: unlike journals, it is useful
    # WHILE the run is alive, so print the watch command
    if (
        os.environ.get("MPIT_OBS_LIVE", "0") not in ("", "0")
        and os.environ.get("MPIT_OBS_DIR")
    ):
        print(
            "[launch] LIVE telemetry: snapshots in "
            f"{os.path.join(os.environ['MPIT_OBS_DIR'], 'live')} — watch "
            f"with `python -m mpit_tpu.obs live "
            f"{os.environ['MPIT_OBS_DIR']}`",
            file=sys.stderr,
        )
    # and hung-job forensics: each rank will dump all-thread stacks on a
    # timer (stacks_rank<r>.txt next to the journal, stderr without a dir)
    if os.environ.get("MPIT_OBS_FAULTHANDLER", "0") not in ("", "0"):
        print(
            "[launch] FAULTHANDLER armed in all ranks: periodic "
            "all-thread stack dumps every "
            f"{os.environ['MPIT_OBS_FAULTHANDLER']}"
            " (1 = 300 s default interval)",
            file=sys.stderr,
        )

    # one extra port for the jax.distributed coordinator (rank 0 binds it)
    reserving, ports = _reserve_ports(ns.n + (1 if ns.jax_distributed else 0))
    coord_sock, coord_port = None, None
    if ns.jax_distributed:
        # released right before rank 0 spawns, same as the rank ports —
        # closing it here would open a steal window of the whole launch
        coord_sock, coord_port = reserving.pop(), ports.pop()
    hosts = ",".join(f"127.0.0.1:{port}" for port in ports)

    # elastic supervision knobs (docs/ROBUSTNESS.md "Elastic membership")
    elastic = os.environ.get("MPIT_ELASTIC_RESPAWN", "0") not in ("", "0")
    max_respawns = int(os.environ.get("MPIT_ELASTIC_MAX_RESPAWNS", "3"))
    kill_every = float(os.environ.get("MPIT_ELASTIC_KILL_EVERY_S", "0") or 0)
    kill_seed = int(os.environ.get("MPIT_ELASTIC_KILL_SEED", "0"))
    # restrict the killer's victim pool (comma-separated ranks) — the
    # sharded-PS soak leg aims it at the server ranks so every kill
    # exercises reshard/repair, not just client JOIN
    _kill_ranks = os.environ.get("MPIT_ELASTIC_KILL_RANKS", "").strip()
    kill_ranks = (
        {int(r) for r in _kill_ranks.split(",")} if _kill_ranks else None
    )
    # hold a killed rank down for N seconds before respawning it — an
    # immediate respawn (the default) reconnects before its peers even
    # notice; the delay opens a real dead window so failure paths
    # (reshard/repair, dead-rank declaration) actually run
    respawn_delay = float(
        os.environ.get("MPIT_ELASTIC_RESPAWN_DELAY_S", "0") or 0
    )
    obs_dir = os.environ.get("MPIT_OBS_DIR")
    mem_path = (
        os.path.join(obs_dir, "membership.jsonl")
        if elastic and obs_dir else None
    )
    mem_lock = make_lock("launch.mem_lock")
    t0 = time.monotonic()

    def _member(kind: str, rank: int, gen: int, **extra) -> None:
        """One membership transition in the run's obs directory — the
        ground truth conformance uses to license journal gaps on
        churned ranks (a SIGKILLed process cannot flush its tail).
        ``t`` is run-relative (monotonic since launch); ``wt`` is the
        wall clock, the join key ``obs postmortem`` uses to place a
        kill/exit on the black-box dump timeline."""
        if mem_path is None:
            return
        rec = {
            "ev": "membership", "kind": kind, "rank": rank, "gen": gen,
            "t": round(time.monotonic() - t0, 3),
            "wt": round(time.time(), 3), **extra,
        }
        with mem_lock:
            os.makedirs(os.path.dirname(mem_path), exist_ok=True)
            with open(mem_path, "a") as f:
                f.write(json.dumps(rec) + "\n")

    def _request_blackbox(kind: str, rank: int, gen: int) -> None:
        """Freeze the incident window fleet-wide: ask every surviving
        rank's flight recorder to dump (the dead rank can't — the
        survivors' windows are what still show its final exchanges)."""
        if obs_dir is None:
            return
        try:
            from mpit_tpu.obs.blackbox import request_dump

            request_dump(
                obs_dir, f"launch:{kind}", f"{kind}-rank{rank}-gen{gen}"
            )
        except Exception:
            pass  # forensics must never take the supervisor down

    def _archive_blackbox(rank: int, gen: int) -> None:
        """Before respawning a rank, park its dump file under a
        per-generation name so the next generation's dumps don't
        interleave with the dead one's."""
        if obs_dir is None:
            return
        path = os.path.join(obs_dir, "blackbox", f"rank_{rank}.jsonl")
        try:
            if os.path.exists(path):
                os.replace(
                    path,
                    os.path.join(
                        obs_dir, "blackbox", f"rank_{rank}.gen{gen}.jsonl"
                    ),
                )
        except OSError:
            pass

    procs: list[subprocess.Popen] = []
    streams: list[threading.Thread] = []

    def _spawn(rank: int, gen: int) -> subprocess.Popen:
        env = dict(os.environ)
        env["MPIT_RANK"] = str(rank)
        env["MPIT_WORLD_SIZE"] = str(ns.n)
        env["MPIT_TRANSPORT_HOSTS"] = hosts
        if coord_port is not None:
            env["MPIT_DISTRIBUTED"] = "1"
            env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{coord_port}"
        if elastic:
            env["MPIT_RESPAWN_GEN"] = str(gen)
        proc = subprocess.Popen(
            [sys.executable, ns.script, *ns.args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        t = threading.Thread(
            target=_stream, args=(rank, proc.stdout, sys.stdout.buffer),
            daemon=True,
        )
        t.start()
        streams.append(t)
        _member("respawn" if gen else "spawn", rank, gen)
        return proc

    try:
        for rank in range(ns.n):
            # release this rank's port only now, right before its process
            # exists (and the coordinator port with rank 0, which binds it)
            if rank == 0 and coord_sock is not None:
                coord_sock.close()
            reserving[rank].close()
            procs.append(_spawn(rank, 0))
    except BaseException:
        # a failed spawn mid-loop must not strand reservations (they'd stay
        # bound for the launcher's lifetime) or leave earlier ranks spinning
        # in connect-retry against ports that will never get a listener
        for s in reserving:
            s.close()
        if coord_sock is not None:
            coord_sock.close()
        for proc in procs:
            proc.terminate()
        raise

    # seeded chaos killer: SIGKILL a random respawnable rank on a timer —
    # the soak harness's preemption source (never the last rank standing,
    # never a rank whose respawn budget is spent)
    gens = [0] * ns.n
    budget = [max_respawns if elastic else 0] * ns.n
    procs_lock = make_lock("launch.procs_lock")
    killer_stop = threading.Event()
    if elastic and kill_every > 0:
        rng_k = random.Random(kill_seed)

        def _killer() -> None:
            while not killer_stop.wait(kill_every):
                with procs_lock:
                    alive = [
                        r for r in range(ns.n) if procs[r].poll() is None
                    ]
                    victims = [
                        r for r in alive
                        if budget[r] > 0
                        and (kill_ranks is None or r in kill_ranks)
                    ]
                    if len(alive) <= 1 or not victims:
                        continue
                    r = rng_k.choice(victims)
                    try:
                        procs[r].kill()
                    except (ProcessLookupError, OSError):
                        continue
                    _member("kill", r, gens[r], signal="SIGKILL")
                    _request_blackbox("kill", r, gens[r])

        threading.Thread(
            target=_killer, daemon=True, name="mpit-elastic-killer"
        ).start()

    rc = 0
    try:
        remaining = set(range(ns.n))
        world_down = False
        pending: dict = {}  # rank -> monotonic respawn deadline
        while remaining:
            now = time.monotonic()
            for r in sorted(pending):
                if world_down:
                    pending.pop(r)
                    remaining.discard(r)
                    continue
                if now < pending[r]:
                    continue
                pending.pop(r)
                with procs_lock:
                    procs[r] = _spawn(r, gens[r])
                print(
                    f"[launch] rank {r} respawned as gen {gens[r]} "
                    f"after {respawn_delay:g}s hold "
                    f"({budget[r]} respawn(s) left)",
                    file=sys.stderr,
                )
            for r in sorted(remaining):
                if r in pending:
                    continue  # held down: its exit is already handled
                code = procs[r].poll()
                if code is None:
                    continue
                if code == 0:
                    remaining.discard(r)
                    _member("done", r, gens[r])
                    continue
                if world_down:
                    remaining.discard(r)
                    continue
                # a negative returncode is death-by-signal: name it, so
                # the post-mortem can cite "exit by SIGKILL" not "-9"
                cause = {"code": code}
                if code < 0:
                    try:
                        cause["signal"] = signal.Signals(-code).name
                    except ValueError:
                        pass
                _member("exit", r, gens[r], **cause)
                _request_blackbox("exit", r, gens[r])
                if budget[r] > 0:
                    # elastic: the rank died with budget left — respawn it
                    # in place (same rank/port, next generation) instead
                    # of taking the world down
                    # budget/gens are read by the killer thread under
                    # procs_lock — mutate them under the same lock
                    with procs_lock:
                        budget[r] -= 1
                        gens[r] += 1
                    _archive_blackbox(r, gens[r] - 1)
                    if respawn_delay > 0:
                        pending[r] = time.monotonic() + respawn_delay
                        print(
                            f"[launch] rank {r} exited with {code}; "
                            f"holding down {respawn_delay:g}s before "
                            f"gen {gens[r]}",
                            file=sys.stderr,
                        )
                        continue
                    with procs_lock:
                        procs[r] = _spawn(r, gens[r])
                    print(
                        f"[launch] rank {r} exited with {code}; "
                        f"respawned as gen {gens[r]} "
                        f"({budget[r]} respawn(s) left)",
                        file=sys.stderr,
                    )
                    continue
                remaining.discard(r)
                if rc == 0:
                    rc = code
                print(
                    f"[launch] rank {r} exited with {code}; "
                    "terminating the world",
                    file=sys.stderr,
                )
                world_down = True
                for other in sorted(remaining):
                    procs[other].terminate()
            if remaining:
                waitable = [r for r in remaining if r not in pending]
                if waitable:
                    try:
                        procs[min(waitable)].wait(timeout=0.2)
                    except subprocess.TimeoutExpired:
                        pass
                else:
                    # every live rank is held down: a dead proc's wait()
                    # returns instantly, so sleep instead of spinning
                    time.sleep(0.2)
    except KeyboardInterrupt:
        for proc in procs:
            proc.send_signal(signal.SIGINT)
        rc = 130
    finally:
        killer_stop.set()
    for proc in procs:
        proc.wait()
    for t in streams:
        t.join(timeout=2)
    return rc


if __name__ == "__main__":
    sys.exit(main())
