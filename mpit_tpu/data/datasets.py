"""Dataset loaders with on-disk fast path + synthetic fallback.

On-disk formats supported when present under ``$MPIT_DATA_DIR``:
- MNIST: the standard idx files (``train-images-idx3-ubyte`` etc.), parsed
  in numpy.
- CIFAR-10: the standard binary batches (``data_batch_1..5.bin`` +
  ``test_batch.bin``), or an ``.npz`` cache; synthetic CIFAR-shaped data
  otherwise.

Everything returns plain numpy; device placement and sharding are the
trainers' job (data loading stays on host, off the TPU hot path).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Iterator, Optional

import numpy as np

from mpit_tpu.data.synthetic import (
    synthetic_image_classification,
    synthetic_lm_corpus,
)


def _data_dir() -> Optional[str]:
    d = os.environ.get("MPIT_DATA_DIR")
    return d if d and os.path.isdir(d) else None


def _read_idx(path: str) -> np.ndarray:
    """Parse an MNIST idx file (optionally gzipped)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(dirname: str, stem: str) -> Optional[str]:
    for suffix in ("", ".gz"):
        p = os.path.join(dirname, stem + suffix)
        if os.path.exists(p):
            return p
    return None


def load_mnist(synthetic_train: int = 8192, synthetic_test: int = 2048):
    """MNIST as (x_train, y_train, x_test, y_test), images (N,28,28,1) in
    [0,1]. Falls back to learnable synthetic data when no files exist."""
    d = _data_dir()
    if d:
        paths = {
            "xtr": _find(d, "train-images-idx3-ubyte"),
            "ytr": _find(d, "train-labels-idx1-ubyte"),
            "xte": _find(d, "t10k-images-idx3-ubyte"),
            "yte": _find(d, "t10k-labels-idx1-ubyte"),
        }
        if all(paths.values()):
            x_tr = _read_idx(paths["xtr"]).astype(np.float32)[..., None] / 255.0
            y_tr = _read_idx(paths["ytr"]).astype(np.int32)
            x_te = _read_idx(paths["xte"]).astype(np.float32)[..., None] / 255.0
            y_te = _read_idx(paths["yte"]).astype(np.int32)
            return x_tr, y_tr, x_te, y_te
    return synthetic_image_classification(
        synthetic_train, synthetic_test, (28, 28, 1), 10, seed=0
    )


def _read_cifar10_bin(paths: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """Parse standard CIFAR-10 binary batches (``data_batch_*.bin`` /
    ``test_batch.bin``): records of 1 label byte + 3072 pixel bytes laid
    out channel-planar (3, 32, 32). Returns (x in NHWC [0,1], y int32)."""
    record = 1 + 3 * 32 * 32
    xs, ys = [], []
    for p in paths:
        opener = gzip.open if p.endswith(".gz") else open
        with opener(p, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        if raw.size == 0 or raw.size % record != 0:
            raise ValueError(
                f"{p}: size {raw.size} is not a multiple of the "
                f"{record}-byte CIFAR-10 record"
            )
        rows = raw.reshape(-1, record)
        ys.append(rows[:, 0].astype(np.int32))
        xs.append(
            rows[:, 1:]
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
            .astype(np.float32)
            / 255.0
        )
    return np.concatenate(xs), np.concatenate(ys)


def has_real_dataset(name: str) -> bool:
    """True iff the matching loader would read REAL files (not the
    synthetic fallback). The conditions here restate each loader's own
    file checks exactly — keep them in lockstep when editing a loader
    (scripts/acceptance.py gates real-data acceptance runs on this).
    """
    if name not in ("mnist", "cifar10", "ptb", "imagenet"):
        raise ValueError(f"unknown dataset {name!r}")
    d = _data_dir()
    if not d:
        return False
    if name == "mnist":
        return all(
            _find(d, n)
            for n in (
                "train-images-idx3-ubyte", "train-labels-idx1-ubyte",
                "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte",
            )
        )
    if name == "cifar10":
        for sub in ("", "cifar-10-batches-bin"):
            base = os.path.join(d, sub) if sub else d
            if (
                all(
                    _find(base, f"data_batch_{i}.bin")
                    for i in range(1, 6)
                )
                and _find(base, "test_batch.bin")
            ):
                return True
        return os.path.exists(os.path.join(d, "cifar10.npz"))
    if name == "ptb":
        return os.path.exists(
            os.path.join(d, "ptb.train.txt")
        ) and os.path.exists(os.path.join(d, "ptb.valid.txt"))
    train = os.path.join(d, "imagenet", "train")
    return os.path.isdir(train) and any(
        os.path.isdir(os.path.join(train, e)) for e in os.listdir(train)
    )


def load_cifar10(synthetic_train: int = 8192, synthetic_test: int = 2048):
    """CIFAR-10 as (x_train, y_train, x_test, y_test), images (N,32,32,3)
    in [0,1]. Prefers the standard binary batches (``data_batch_1..5.bin``
    + ``test_batch.bin``, optionally gzipped, under ``$MPIT_DATA_DIR``
    directly or in a ``cifar-10-batches-bin/`` subdir), then an ``.npz``
    cache, then learnable synthetic data."""
    d = _data_dir()
    if d:
        for sub in ("", "cifar-10-batches-bin"):
            base = os.path.join(d, sub) if sub else d
            train = [
                _find(base, f"data_batch_{i}.bin") for i in range(1, 6)
            ]
            test = _find(base, "test_batch.bin")
            if all(train) and test:
                x_tr, y_tr = _read_cifar10_bin(train)
                x_te, y_te = _read_cifar10_bin([test])
                return x_tr, y_tr, x_te, y_te
        p = os.path.join(d, "cifar10.npz")
        if os.path.exists(p):
            z = np.load(p)
            return (
                z["x_train"].astype(np.float32),
                z["y_train"].astype(np.int32),
                z["x_test"].astype(np.float32),
                z["y_test"].astype(np.int32),
            )
    return synthetic_image_classification(
        synthetic_train, synthetic_test, (32, 32, 3), 10, seed=1
    )


_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def _read_image_folder(
    root: str,
    image_size: int,
    limit: Optional[int] = None,
    classes: Optional[list[str]] = None,
) -> tuple[np.ndarray, np.ndarray, list[str]]:
    """Decode a class-per-subdirectory image tree (the standard ImageNet
    train/val layout) into (x NHWC [0,1], y int32, class_names). Images are
    resized so the short side is ``image_size`` then center-cropped — the
    standard eval transform. ``limit`` caps total images (the loader holds
    everything in host RAM, like every loader in this module), spread as an
    even per-class cap so every class stays represented. ``classes`` pins
    the label mapping (pass the train split's list when loading val so
    labels agree across splits; unknown subdirs are an error)."""
    from PIL import Image

    subdirs = sorted(
        e for e in os.listdir(root)
        if os.path.isdir(os.path.join(root, e))
    )
    if not subdirs:
        raise ValueError(f"{root}: no class subdirectories")
    if classes is None:
        classes = subdirs
    else:
        unknown = sorted(set(subdirs) - set(classes))
        if unknown:
            raise ValueError(
                f"{root}: subdirectories {unknown} not in the training "
                f"class list — splits must share one label mapping"
            )
    label_of = {c: i for i, c in enumerate(classes)}
    per_class = (
        None if limit is None else max(1, limit // len(subdirs))
    )
    xs, ys = [], []
    for cls in subdirs:
        cdir = os.path.join(root, cls)
        taken = 0
        if limit is not None and len(xs) >= limit:
            break  # the total cap is a hard RAM bound and wins over coverage
        for fname in sorted(os.listdir(cdir)):
            if not fname.lower().endswith(_IMAGE_EXTS):
                continue
            if per_class is not None and taken >= per_class:
                break
            if limit is not None and len(xs) >= limit:
                break
            with Image.open(os.path.join(cdir, fname)) as im:
                im = im.convert("RGB")
                w, h = im.size
                scale = image_size / min(w, h)
                im = im.resize(
                    (max(image_size, round(w * scale)),
                     max(image_size, round(h * scale)))
                )
                left = (im.size[0] - image_size) // 2
                top = (im.size[1] - image_size) // 2
                im = im.crop(
                    (left, top, left + image_size, top + image_size)
                )
                xs.append(np.asarray(im, dtype=np.float32) / 255.0)
                ys.append(label_of[cls])
            taken += 1
    if not xs:
        raise ValueError(
            f"{root}: class subdirectories contain no decodable images "
            f"(supported extensions: {', '.join(_IMAGE_EXTS)})"
        )
    return np.stack(xs), np.array(ys, dtype=np.int32), classes


def load_imagenet_like(
    synthetic_train: int = 2048,
    synthetic_test: int = 512,
    image_size: int = 224,
    num_classes: int = 1000,
):
    """ImageNet-shaped data for the AlexNet/ResNet-50 configs
    (BASELINE.json:9-10). When ``$MPIT_DATA_DIR/imagenet/train`` (+
    ``val``) holds the standard class-per-subdir image tree it is decoded
    for real (PIL; resize-short-side + center-crop; in-RAM). Per-split
    image counts are capped at what the caller asked for
    (``synthetic_train``/``synthetic_test``, i.e. the config's
    ``train_size``) unless ``$MPIT_IMAGENET_LIMIT`` overrides both caps;
    the cap is spread evenly across classes. Otherwise synthetic data of
    the right shape — the throughput benchmark only needs shape."""
    d = _data_dir()
    if d:
        train_dir = os.path.join(d, "imagenet", "train")
        val_dir = os.path.join(d, "imagenet", "val")
        if os.path.isdir(train_dir):
            env_limit = os.environ.get("MPIT_IMAGENET_LIMIT")
            tr_limit = int(env_limit) if env_limit else synthetic_train
            te_limit = int(env_limit) if env_limit else synthetic_test
            x_tr, y_tr, classes = _read_image_folder(
                train_dir, image_size, tr_limit
            )
            if len(classes) > num_classes:
                raise ValueError(
                    f"{train_dir}: {len(classes)} class subdirectories "
                    f"exceed the model head's num_classes={num_classes}; "
                    "labels would be out of range for the logits"
                )
            if os.path.isdir(val_dir):
                x_te, y_te, _ = _read_image_folder(
                    val_dir, image_size, te_limit, classes=classes
                )
            else:  # no val split: hold out a shuffled slice of train
                perm = np.random.default_rng(0).permutation(len(x_tr))
                x_tr, y_tr = x_tr[perm], y_tr[perm]
                cut = max(1, len(x_tr) // 10)
                x_te, y_te = x_tr[-cut:], y_tr[-cut:]
                x_tr, y_tr = x_tr[:-cut], y_tr[:-cut]
            return x_tr, y_tr, x_te, y_te
    return synthetic_image_classification(
        synthetic_train,
        synthetic_test,
        (image_size, image_size, 3),
        num_classes,
        seed=2,
    )


def load_ptb(
    synthetic_tokens: int = 200_000, vocab_size: int = 10_000
) -> tuple[np.ndarray, np.ndarray, int]:
    """PTB-shaped token streams (train, valid, vocab_size). Real PTB
    (``ptb.train.txt``/``ptb.valid.txt`` under $MPIT_DATA_DIR) when present;
    synthetic Markov corpus otherwise."""
    d = _data_dir()
    if d:
        tr = os.path.join(d, "ptb.train.txt")
        va = os.path.join(d, "ptb.valid.txt")
        if os.path.exists(tr) and os.path.exists(va):
            with open(tr) as f:
                train_words = f.read().replace("\n", " <eos> ").split()
            with open(va) as f:
                valid_words = f.read().replace("\n", " <eos> ").split()
            vocab = {w: i for i, w in enumerate(sorted(set(train_words)))}
            unk = vocab.get("<unk>", 0)
            t = np.array([vocab[w] for w in train_words], dtype=np.int32)
            v = np.array(
                [vocab.get(w, unk) for w in valid_words], dtype=np.int32
            )
            return t, v, len(vocab)
    toks = synthetic_lm_corpus(synthetic_tokens, vocab_size, seed=3)
    split = int(len(toks) * 0.9)
    return toks[:split], toks[split:], vocab_size


def shard_for_worker(
    x: np.ndarray, worker: int, num_workers: int
) -> np.ndarray:
    """Static per-worker shard by worker id (reference: per-rank split,
    SURVEY.md §2 comp. 8). Truncates to equal shard sizes — SPMD needs
    identical shapes per worker."""
    per = len(x) // num_workers
    return x[worker * per : (worker + 1) * per]


@dataclasses.dataclass
class Batches:
    """Host-side minibatch iterator producing *global* batches.

    Yields arrays with leading dim ``global_batch = per_worker_batch * W``;
    the trainer shards the leading axis onto the worker mesh axis. Shuffles
    per epoch with a deterministic seed (reproducible across restarts —
    checkpoint/resume needs this). The trailing remainder of each epoch is
    always dropped: SPMD steps need identical batch shapes."""

    x: np.ndarray
    y: np.ndarray
    global_batch: int
    seed: int = 0

    def __post_init__(self):
        if len(self.x) != len(self.y):
            raise ValueError("x and y length mismatch")
        if len(self.x) < self.global_batch:
            raise ValueError(
                f"dataset of {len(self.x)} samples cannot fill one global "
                f"batch of {self.global_batch}"
            )

    def epoch(self, epoch_index: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        rng = np.random.default_rng(self.seed + epoch_index)
        order = rng.permutation(len(self.x))
        n_full = len(self.x) // self.global_batch
        for b in range(n_full):
            idx = order[b * self.global_batch : (b + 1) * self.global_batch]
            yield self.x[idx], self.y[idx]

    def steps_per_epoch(self) -> int:
        return len(self.x) // self.global_batch


INPUT_DTYPES = ("float32", "bf16")


def cast_input_dtype(x: np.ndarray, dtype_name: str) -> np.ndarray:
    """Cast a float input array to the staging dtype (``float32`` | ``bf16``).

    ``bf16`` stages inputs as bfloat16 on host (via ml_dtypes), halving the
    host->device transfer bytes and the HBM read traffic of the first layer.
    Models already compute in bfloat16 (they cast inputs on entry), so this
    moves the existing cast from device to host — the conv consumes the
    exact same bf16 values either way; only the storage narrows. Integer
    inputs (token ids) pass through untouched: embedding lookups need exact
    indices and gain nothing from narrowing.
    """
    if dtype_name not in INPUT_DTYPES:
        raise ValueError(
            f"unknown input dtype {dtype_name!r}; have {INPUT_DTYPES}"
        )
    if dtype_name == "float32" or not np.issubdtype(x.dtype, np.floating):
        return x
    import ml_dtypes

    return x.astype(ml_dtypes.bfloat16)
