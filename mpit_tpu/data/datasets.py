"""Dataset loaders with on-disk fast path + synthetic fallback.

On-disk formats supported when present under ``$MPIT_DATA_DIR``:
- MNIST: the standard idx files (``train-images-idx3-ubyte`` etc.), parsed
  in numpy.
- CIFAR-10: the standard binary batches (``data_batch_1..5.bin`` +
  ``test_batch.bin``), or an ``.npz`` cache; synthetic CIFAR-shaped data
  otherwise.

Everything returns plain numpy; device placement and sharding are the
trainers' job (data loading stays on host, off the TPU hot path).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import struct
from typing import Iterator, Optional

import numpy as np

from mpit_tpu.data.synthetic import (
    synthetic_image_classification,
    synthetic_lm_corpus,
)


def _data_dir() -> Optional[str]:
    d = os.environ.get("MPIT_DATA_DIR")
    return d if d and os.path.isdir(d) else None


def _read_idx(path: str) -> np.ndarray:
    """Parse an MNIST idx file (optionally gzipped)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(dirname: str, stem: str) -> Optional[str]:
    for suffix in ("", ".gz"):
        p = os.path.join(dirname, stem + suffix)
        if os.path.exists(p):
            return p
    return None


def load_mnist(synthetic_train: int = 8192, synthetic_test: int = 2048):
    """MNIST as (x_train, y_train, x_test, y_test), images (N,28,28,1) in
    [0,1]. Falls back to learnable synthetic data when no files exist."""
    d = _data_dir()
    if d:
        paths = {
            "xtr": _find(d, "train-images-idx3-ubyte"),
            "ytr": _find(d, "train-labels-idx1-ubyte"),
            "xte": _find(d, "t10k-images-idx3-ubyte"),
            "yte": _find(d, "t10k-labels-idx1-ubyte"),
        }
        if all(paths.values()):
            x_tr = _read_idx(paths["xtr"]).astype(np.float32)[..., None] / 255.0
            y_tr = _read_idx(paths["ytr"]).astype(np.int32)
            x_te = _read_idx(paths["xte"]).astype(np.float32)[..., None] / 255.0
            y_te = _read_idx(paths["yte"]).astype(np.int32)
            return x_tr, y_tr, x_te, y_te
    return synthetic_image_classification(
        synthetic_train, synthetic_test, (28, 28, 1), 10, seed=0
    )


def _read_cifar10_bin(paths: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """Parse standard CIFAR-10 binary batches (``data_batch_*.bin`` /
    ``test_batch.bin``): records of 1 label byte + 3072 pixel bytes laid
    out channel-planar (3, 32, 32). Returns (x in NHWC [0,1], y int32)."""
    record = 1 + 3 * 32 * 32
    xs, ys = [], []
    for p in paths:
        opener = gzip.open if p.endswith(".gz") else open
        with opener(p, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        if raw.size == 0 or raw.size % record != 0:
            raise ValueError(
                f"{p}: size {raw.size} is not a multiple of the "
                f"{record}-byte CIFAR-10 record"
            )
        rows = raw.reshape(-1, record)
        ys.append(rows[:, 0].astype(np.int32))
        xs.append(
            rows[:, 1:]
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
            .astype(np.float32)
            / 255.0
        )
    return np.concatenate(xs), np.concatenate(ys)


def load_cifar10(synthetic_train: int = 8192, synthetic_test: int = 2048):
    """CIFAR-10 as (x_train, y_train, x_test, y_test), images (N,32,32,3)
    in [0,1]. Prefers the standard binary batches (``data_batch_1..5.bin``
    + ``test_batch.bin``, optionally gzipped, under ``$MPIT_DATA_DIR``
    directly or in a ``cifar-10-batches-bin/`` subdir), then an ``.npz``
    cache, then learnable synthetic data."""
    d = _data_dir()
    if d:
        for sub in ("", "cifar-10-batches-bin"):
            base = os.path.join(d, sub) if sub else d
            train = [
                _find(base, f"data_batch_{i}.bin") for i in range(1, 6)
            ]
            test = _find(base, "test_batch.bin")
            if all(train) and test:
                x_tr, y_tr = _read_cifar10_bin(train)
                x_te, y_te = _read_cifar10_bin([test])
                return x_tr, y_tr, x_te, y_te
        p = os.path.join(d, "cifar10.npz")
        if os.path.exists(p):
            z = np.load(p)
            return (
                z["x_train"].astype(np.float32),
                z["y_train"].astype(np.int32),
                z["x_test"].astype(np.float32),
                z["y_test"].astype(np.int32),
            )
    return synthetic_image_classification(
        synthetic_train, synthetic_test, (32, 32, 3), 10, seed=1
    )


def load_imagenet_like(
    synthetic_train: int = 2048,
    synthetic_test: int = 512,
    image_size: int = 224,
    num_classes: int = 1000,
):
    """ImageNet-shaped synthetic data for the AlexNet/ResNet-50 configs
    (BASELINE.json:9-10). Real ImageNet is out of scope in this image; the
    benchmark measures throughput, for which shape is what matters."""
    return synthetic_image_classification(
        synthetic_train,
        synthetic_test,
        (image_size, image_size, 3),
        num_classes,
        seed=2,
    )


def load_ptb(
    synthetic_tokens: int = 200_000, vocab_size: int = 10_000
) -> tuple[np.ndarray, np.ndarray, int]:
    """PTB-shaped token streams (train, valid, vocab_size). Real PTB
    (``ptb.train.txt``/``ptb.valid.txt`` under $MPIT_DATA_DIR) when present;
    synthetic Markov corpus otherwise."""
    d = _data_dir()
    if d:
        tr = os.path.join(d, "ptb.train.txt")
        va = os.path.join(d, "ptb.valid.txt")
        if os.path.exists(tr) and os.path.exists(va):
            with open(tr) as f:
                train_words = f.read().replace("\n", " <eos> ").split()
            with open(va) as f:
                valid_words = f.read().replace("\n", " <eos> ").split()
            vocab = {w: i for i, w in enumerate(sorted(set(train_words)))}
            unk = vocab.get("<unk>", 0)
            t = np.array([vocab[w] for w in train_words], dtype=np.int32)
            v = np.array(
                [vocab.get(w, unk) for w in valid_words], dtype=np.int32
            )
            return t, v, len(vocab)
    toks = synthetic_lm_corpus(synthetic_tokens, vocab_size, seed=3)
    split = int(len(toks) * 0.9)
    return toks[:split], toks[split:], vocab_size


def shard_for_worker(
    x: np.ndarray, worker: int, num_workers: int
) -> np.ndarray:
    """Static per-worker shard by worker id (reference: per-rank split,
    SURVEY.md §2 comp. 8). Truncates to equal shard sizes — SPMD needs
    identical shapes per worker."""
    per = len(x) // num_workers
    return x[worker * per : (worker + 1) * per]


@dataclasses.dataclass
class Batches:
    """Host-side minibatch iterator producing *global* batches.

    Yields arrays with leading dim ``global_batch = per_worker_batch * W``;
    the trainer shards the leading axis onto the worker mesh axis. Shuffles
    per epoch with a deterministic seed (reproducible across restarts —
    checkpoint/resume needs this). The trailing remainder of each epoch is
    always dropped: SPMD steps need identical batch shapes."""

    x: np.ndarray
    y: np.ndarray
    global_batch: int
    seed: int = 0

    def __post_init__(self):
        if len(self.x) != len(self.y):
            raise ValueError("x and y length mismatch")
        if len(self.x) < self.global_batch:
            raise ValueError(
                f"dataset of {len(self.x)} samples cannot fill one global "
                f"batch of {self.global_batch}"
            )

    def epoch(self, epoch_index: int) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        rng = np.random.default_rng(self.seed + epoch_index)
        order = rng.permutation(len(self.x))
        n_full = len(self.x) // self.global_batch
        for b in range(n_full):
            idx = order[b * self.global_batch : (b + 1) * self.global_batch]
            yield self.x[idx], self.y[idx]

    def steps_per_epoch(self) -> int:
        return len(self.x) // self.global_batch
