"""Device-prefetching input pipeline.

The reference fed Torch tensors from host RAM synchronously inside its
training loop (SURVEY.md §2 comp. 8) — fine for a CPU-bound Lua harness,
but on TPU a synchronous host→device copy in the step path serializes the
PCIe/tunnel transfer with the compute. The TPU-native pattern is to stage
upcoming batches into HBM *while the current step runs*: ``jax.device_put``
is asynchronous (it returns immediately and the transfer proceeds in the
background), so holding a small deque of already-dispatched batches ahead
of the consumer overlaps transfer with compute at zero thread cost.

Staging uses the step's own input sharding (leading worker axis) — a default
``device_put`` would commit to device 0 and push a redistribute back into
every step (the same trap bench.py's staging avoids).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    it: Iterable[Any],
    sharding,
    depth: int = 2,
) -> Iterator[Any]:
    """Yield items of ``it`` (pytrees of host arrays) staged on device.

    ``depth`` batches are dispatched ahead of the consumer; ``depth=0``
    degrades to synchronous per-item staging. The sharding is applied to
    every array leaf. Each staged item costs its full HBM footprint until
    consumed — peak input memory is ``depth + 1`` items.
    """
    if depth < 0:  # validate eagerly, not at first next()
        raise ValueError(f"depth must be >= 0, got {depth}")
    return _prefetch_gen(it, sharding, depth)


def _prefetch_gen(it, sharding, depth) -> Iterator[Any]:
    buf: deque = deque()
    for item in it:
        # device_put maps one sharding over every leaf of a pytree itself
        buf.append(jax.device_put(item, sharding))
        if len(buf) > depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


class DeviceBatches:
    """A :class:`~mpit_tpu.data.Batches`-shaped epoch iterator whose batches
    arrive already sharded onto the worker mesh axis, ``depth`` ahead.

    Wraps any object with ``epoch(i)`` / ``steps_per_epoch()`` (the Batches
    protocol). An optional ``transform(x, y) -> item`` reshapes each host
    batch before staging (e.g. a τ-round regrouping); by default items are
    the ``(x, y)`` pairs unchanged.
    """

    def __init__(
        self,
        batches,
        topo,
        depth: int = 2,
        transform: Optional[Callable] = None,
    ):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        self.batches = batches
        self.topo = topo
        self.depth = int(depth)
        self.transform = transform

    def steps_per_epoch(self) -> int:
        return self.batches.steps_per_epoch()

    def epoch(self, epoch_index: int) -> Iterator[Any]:
        sharding = self.topo.worker_sharding()
        it = self.batches.epoch(epoch_index)
        if self.transform is not None:
            it = (self.transform(x, y) for x, y in it)
        return prefetch_to_device(it, sharding, depth=self.depth)
