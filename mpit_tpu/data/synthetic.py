"""Deterministic, learnable synthetic datasets.

Design: each class c gets a fixed random template T_c (seeded PRNG); a sample
is ``clip(intensity * T_c + noise)``. Linearly separable enough that LeNet /
VGG reach high accuracy in a few hundred steps, noisy enough that training
dynamics (loss curves, convergence of EASGD centers) are non-trivial — which
is what the e2e tests and benchmarks need from data in a zero-egress image.
"""

from __future__ import annotations

import numpy as np


def synthetic_image_classification(
    num_train: int,
    num_test: int,
    image_shape: tuple[int, int, int],
    num_classes: int,
    seed: int = 0,
    noise: float = 0.35,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Returns (x_train, y_train, x_test, y_test); images float32 in [0, 1],
    labels int32."""
    rng = np.random.default_rng(seed)
    templates = rng.uniform(0.0, 1.0, size=(num_classes, *image_shape)).astype(
        np.float32
    )

    def make(n: int, split_seed: int):
        r = np.random.default_rng(seed + split_seed)
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        intensity = r.uniform(0.7, 1.3, size=(n, 1, 1, 1)).astype(np.float32)
        x = templates[y] * intensity + r.normal(
            0.0, noise, size=(n, *image_shape)
        ).astype(np.float32)
        return np.clip(x, 0.0, 1.0), y

    x_tr, y_tr = make(num_train, 1)
    x_te, y_te = make(num_test, 2)
    return x_tr, y_tr, x_te, y_te


def synthetic_lm_corpus(
    num_tokens: int, vocab_size: int, seed: int = 0, order: int = 2
) -> np.ndarray:
    """A synthetic token stream with learnable Markov structure.

    Tokens follow a sparse ``order``-gram chain (each context maps to a small
    set of likely successors), so an LSTM achieves materially lower perplexity
    than the uniform baseline — enough signal for PTB-config tests
    (BASELINE.json:11) without shipping the corpus.
    """
    rng = np.random.default_rng(seed)
    branch = 4
    successors = rng.integers(
        0, vocab_size, size=(vocab_size, branch)
    )  # per-context candidate sets (order-1 chain is plenty)
    tokens = np.empty(num_tokens, dtype=np.int32)
    tokens[0] = rng.integers(0, vocab_size)
    picks = rng.integers(0, branch, size=num_tokens)
    mistakes = rng.random(num_tokens) < 0.1  # 10% uniform noise
    randoms = rng.integers(0, vocab_size, size=num_tokens)
    for i in range(1, num_tokens):
        if mistakes[i]:
            tokens[i] = randoms[i]
        else:
            tokens[i] = successors[tokens[i - 1], picks[i]]
    return tokens
