"""Datasets: the TPU-native equivalent of the reference's data loading.

Reference parity (SURVEY.md §2 comp. 8): the reference loaded MNIST (and, in
the driver configs, CIFAR-10/ImageNet/PTB) via torch dataset packages, with
per-rank sharding by worker id. Here every dataset is exposed as numpy arrays
with (a) an on-disk loader for the standard binary formats when files are
present under ``$MPIT_DATA_DIR``, and (b) a deterministic *learnable*
synthetic fallback for network-less environments — class-conditional patterns
a real model trains to high accuracy on, so end-to-end convergence tests are
meaningful without downloads.

Per-worker sharding is a pure function of (process_rank, worker id), matching
the reference's rank-based splits.
"""

from mpit_tpu.data.synthetic import (  # noqa: F401
    synthetic_image_classification,
    synthetic_lm_corpus,
)
from mpit_tpu.data.datasets import (  # noqa: F401
    INPUT_DTYPES,
    cast_input_dtype,
    load_mnist,
    load_cifar10,
    load_imagenet_like,
    load_ptb,
    shard_for_worker,
    Batches,
)
from mpit_tpu.data.prefetch import (  # noqa: F401
    DeviceBatches,
    prefetch_to_device,
)
