"""Real-data acceptance runs: execute whenever ``$MPIT_DATA_DIR`` gains data.

The one BASELINE acceptance criterion this image cannot evaluate is
real-data accuracy (BASELINE.md "MNIST async-SGD accuracy ≈99%"): no
dataset files exist here, so training runs on learnable synthetic
fallbacks. The loaders are ready — this script closes the loop the moment
data appears:

    MPIT_DATA_DIR=/path/to/datasets python scripts/acceptance.py

It probes which real datasets are present (same path rules as
``mpit_tpu.data.datasets``), runs the matching BASELINE acceptance
config(s) end to end, asserts the MNIST ≈99% target, and appends one JSON
line per run to ``ACCEPTANCE.jsonl`` at the repo root.

With no real data it exits 2 after printing what it looked for — wiring
it into cron/CI is safe before the data shows up.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpit_tpu.data import datasets as ds  # noqa: E402
from mpit_tpu.utils.config import TrainConfig  # noqa: E402

# dataset -> (acceptance preset, overrides, accuracy floor or None).
# Presence of REAL files is decided by datasets.has_real_dataset — the
# ONE statement of each loader's file requirements, so a partial dataset
# (e.g. ptb.train.txt without ptb.valid.txt) can never record a
# synthetic-fallback run as a real-data acceptance result.
# MNIST is the reference's own acceptance config (BASELINE.md ≈99%); the
# others are recorded for the table, with no floors.
_ACCEPTANCE = {
    "mnist": ("mnist-easgd", dict(epochs=10), 0.985),
    "cifar10": ("cifar-vgg-sync", dict(epochs=10), None),
    "ptb": ("ptb-lstm-easgd", dict(epochs=5), None),
    "imagenet": ("alexnet-downpour", dict(epochs=2), None),
}


def main() -> int:
    d = ds._data_dir()
    if not d:
        raw = os.environ.get("MPIT_DATA_DIR")
        what = f"{raw!r} is not a directory" if raw else "is unset"
        print(
            f"acceptance: $MPIT_DATA_DIR {what} — point it at a "
            "directory holding MNIST idx / CIFAR-10 bin / ImageNet "
            "class-tree / PTB txt files"
        )
        return 2
    available = {
        name: spec
        for name, spec in _ACCEPTANCE.items()
        if ds.has_real_dataset(name)
    }
    if not available:
        print(
            f"acceptance: no complete real dataset under {d!r}; looked "
            f"for {sorted(_ACCEPTANCE)} (partial file sets fall back to "
            "synthetic data and are deliberately not accepted)"
        )
        return 2

    from mpit_tpu.run import run  # deferred: initializes jax

    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ACCEPTANCE.jsonl",
    )
    failures = []
    for name, (preset, overrides, floor) in sorted(available.items()):
        cfg = dataclasses.replace(
            TrainConfig().apply_preset(preset), **overrides
        )
        print(f"acceptance[{name}]: running {preset} on real data ...")
        t0 = time.time()
        result = run(cfg)
        record = {
            "dataset": name,
            "preset": preset,
            "accuracy": result.get("accuracy"),
            "target": floor,
            "passed": (
                None if floor is None else result.get("accuracy", 0) >= floor
            ),
            "samples_per_sec_per_chip": result.get("samples_per_sec_per_chip"),
            "platform": result.get("platform"),
            "wall_s": round(time.time() - t0, 1),
            "date": time.strftime("%Y-%m-%d"),
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        print(f"acceptance[{name}]: {json.dumps(record)}")
        if record["passed"] is False:
            failures.append(name)
    if failures:
        print(f"acceptance: BELOW TARGET: {failures}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
