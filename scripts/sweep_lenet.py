"""Sweep LeNet EASGD round timing over (per-worker batch, tau) on the live
backend; prints a JSON row per point (µs/round, samples/s/chip, MFU).

Used to pick the headline bench operating point and to produce the README
µs-per-round table (VERDICT round-1 item 3).
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from bench import bench_jax  # noqa: E402


def main():
    batches = [int(b) for b in (sys.argv[1].split(",") if len(sys.argv) > 1
                                else ("256", "1024", "4096"))]
    taus = [int(t) for t in (sys.argv[2].split(",") if len(sys.argv) > 2
                             else ("1", "4", "16"))]
    for pwb in batches:
        for tau in taus:
            res = bench_jax(per_worker_batch=pwb, tau=tau)
            row = {
                "pwb": pwb,
                "tau": tau,
                "us_per_round": round(
                    1e6 * res["timed_seconds"] / res["timed_rounds"], 1
                ),
                "samples_per_sec_per_chip": round(
                    res["samples_per_sec_per_chip"], 1
                ),
                "mfu": res.get("mfu"),
            }
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
