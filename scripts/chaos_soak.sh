#!/usr/bin/env bash
# Multi-seed chaos soak: run the slow chaos suite across N seed offsets.
#
#   scripts/chaos_soak.sh [N_SEEDS] [MAX_SECONDS]
#
# Each round shifts every schedule seed by MPIT_CHAOS_SOAK_OFFSET (read by
# nothing else — the parametrized seeds in tests/test_chaos.py stay the
# tier-1 contract; the offset just widens the swept space here). Wall-clock
# is bounded: the loop stops starting new rounds once MAX_SECONDS (default
# 600) is spent, so CI can pin a budget without killing a round midway.
# After the sweep, one live-armed 3-rank process round runs and gates on
# the alert engine (`obs live --once`): unexpected alerts exit nonzero.
# A second 3-rank round runs with MPIT_RT_RACE=1 — every rank arms the
# vector-clock race sanitizer (RT103, docs/ANALYSIS.md) and a healthy
# run must report zero findings from every process. A third runs with
# MPIT_RT_NUMERICS=1 under int8 quantization — every rank arms the
# numerics sanitizer (RT104) and a healthy quantized run must likewise
# report zero findings from every process.
set -euo pipefail
cd "$(dirname "$0")/.."

N_SEEDS="${1:-5}"
MAX_SECONDS="${2:-600}"
START=$SECONDS
FAILED=0

for ((i = 0; i < N_SEEDS; i++)); do
  if ((SECONDS - START >= MAX_SECONDS)); then
    echo "chaos_soak: budget of ${MAX_SECONDS}s spent after ${i} round(s); stopping" >&2
    break
  fi
  echo "=== chaos soak round $((i + 1))/${N_SEEDS} (seed offset ${i}) ==="
  if ! env JAX_PLATFORMS=cpu MPIT_CHAOS_SOAK_OFFSET="${i}" \
      python -m pytest tests/test_chaos.py -q -m slow \
      -p no:cacheprovider -p no:xdist -p no:randomly; then
    FAILED=1
    break
  fi
done

if ((FAILED)); then
  echo "chaos_soak: FAILED at seed offset ${i} — replay with:" >&2
  echo "  MPIT_CHAOS_SOAK_OFFSET=${i} python -m pytest tests/test_chaos.py -m slow" >&2
  exit 1
fi

# One live-armed process-mode round on top of the seed sweep: a healthy
# 3-rank run must come out alert-free — any dead-rank/straggler firing
# here is a regression in either the trainer or the alert thresholds.
# (--straggler-spread is loosened: two client threads sharing CPU cores
# legitimately skew more than two real chips would.)
if ((SECONDS - START < MAX_SECONDS)); then
  echo "=== chaos soak: live-armed 3-rank round ===" >&2
  OUT="$(mktemp -d)"
  trap 'rm -rf "$OUT"' EXIT
  env JAX_PLATFORMS=cpu \
      MPIT_OBS_DIR="$OUT" MPIT_OBS_LIVE=1 MPIT_OBS_LIVE_INTERVAL=0.25 \
      timeout -k 10 120 \
      python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
      --model mlp --steps 16 --train-size 256 --algo ps-easgd
  python -m mpit_tpu.obs live "$OUT" --once --json --straggler-spread 0.6
  rm -rf "$OUT"
  trap - EXIT
else
  echo "chaos_soak: budget spent; skipping live-armed round" >&2
fi

# RT103-armed round: the same healthy 3-rank shape with the runtime race
# sanitizer on in every rank process. The gate is two-sided — the armed
# marker must appear (the knob can't silently rot) and no rank may
# report a race (the annotated PServer/Broker hot paths must stay
# lock-ordered under real traffic).
if ((SECONDS - START < MAX_SECONDS)); then
  echo "=== chaos soak: RT103-armed 3-rank round ===" >&2
  OUT="$(mktemp -d)"
  LOG="$OUT/rt_race.log"
  trap 'rm -rf "$OUT"' EXIT
  env JAX_PLATFORMS=cpu MPIT_RT_RACE=1 MPIT_OBS_DIR="$OUT" \
      timeout -k 10 120 \
      python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
      --model mlp --steps 16 --train-size 256 --algo ps-easgd \
      2>&1 | tee "$LOG"
  if ! grep -q "rt-race.*armed" "$LOG"; then
    echo "chaos_soak: MPIT_RT_RACE=1 never armed the sanitizer" >&2
    exit 1
  fi
  if grep "\[rt-race\]" "$LOG" | grep -v "armed" | grep -qv " 0 finding(s)"; then
    echo "chaos_soak: RT103 reported race finding(s):" >&2
    grep -B1 -A12 "RT103\|race on" "$LOG" >&2 || true
    exit 1
  fi
  rm -rf "$OUT"
  trap - EXIT
else
  echo "chaos_soak: budget spent; skipping RT103-armed round" >&2
fi

# RT104-armed round: the same 3-rank shape with int8 quantized pushes
# and the runtime numerics sanitizer on in every rank process. The gate
# is two-sided and per-process — the armed marker must appear in ALL
# THREE processes (the knob can't silently rot, and a rank that never
# armed proves nothing), and no rank may report a numerics finding
# (quantize/dequantize edge cases, server apply NaN/Inf, EF-residual
# boundedness must all hold under real quantized traffic).
if ((SECONDS - START < MAX_SECONDS)); then
  echo "=== chaos soak: RT104-armed 3-rank round (int8) ===" >&2
  OUT="$(mktemp -d)"
  LOG="$OUT/rt_numerics.log"
  trap 'rm -rf "$OUT"' EXIT
  env JAX_PLATFORMS=cpu MPIT_RT_NUMERICS=1 MPIT_WIRE_QUANT=int8 \
      MPIT_OBS_DIR="$OUT" \
      timeout -k 10 120 \
      python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
      --model mlp --steps 16 --train-size 256 --algo ps-easgd \
      2>&1 | tee "$LOG"
  ARMED=$(grep -c "rt-numerics.*armed" "$LOG" || true)
  if ((ARMED < 3)); then
    echo "chaos_soak: MPIT_RT_NUMERICS=1 armed only ${ARMED}/3 processes" >&2
    exit 1
  fi
  if grep "\[rt-numerics\]" "$LOG" | grep -v "armed" | grep -qv " 0 finding(s)"; then
    echo "chaos_soak: RT104 reported numerics finding(s):" >&2
    grep -B1 -A12 "RT104" "$LOG" >&2 || true
    exit 1
  fi
  rm -rf "$OUT"
  trap - EXIT
else
  echo "chaos_soak: budget spent; skipping RT104-armed round" >&2
fi
echo "chaos_soak: OK"
