#!/usr/bin/env bash
# Multi-seed chaos soak: run the slow chaos suite across N seed offsets.
#
#   scripts/chaos_soak.sh [N_SEEDS] [MAX_SECONDS]
#
# Each round shifts every schedule seed by MPIT_CHAOS_SOAK_OFFSET (read by
# nothing else — the parametrized seeds in tests/test_chaos.py stay the
# tier-1 contract; the offset just widens the swept space here). Wall-clock
# is bounded: the loop stops starting new rounds once MAX_SECONDS (default
# 600) is spent, so CI can pin a budget without killing a round midway.
set -euo pipefail
cd "$(dirname "$0")/.."

N_SEEDS="${1:-5}"
MAX_SECONDS="${2:-600}"
START=$SECONDS
FAILED=0

for ((i = 0; i < N_SEEDS; i++)); do
  if ((SECONDS - START >= MAX_SECONDS)); then
    echo "chaos_soak: budget of ${MAX_SECONDS}s spent after ${i} round(s); stopping" >&2
    break
  fi
  echo "=== chaos soak round $((i + 1))/${N_SEEDS} (seed offset ${i}) ==="
  if ! env JAX_PLATFORMS=cpu MPIT_CHAOS_SOAK_OFFSET="${i}" \
      python -m pytest tests/test_chaos.py -q -m slow \
      -p no:cacheprovider -p no:xdist -p no:randomly; then
    FAILED=1
    break
  fi
done

if ((FAILED)); then
  echo "chaos_soak: FAILED at seed offset ${i} — replay with:" >&2
  echo "  MPIT_CHAOS_SOAK_OFFSET=${i} python -m pytest tests/test_chaos.py -m slow" >&2
  exit 1
fi
echo "chaos_soak: OK"
