#!/bin/bash
# Remaining measurement backlog (docs/PERF.md "moment the tunnel returns"
# list, minus the legs already measured 2026-07-31 morning). Ordered by
# value-per-minute: the MFU-ceiling row (ptb-transformer-large) and the
# ResNet-50 profile attribution are VERDICT r3 items 2-3 and run FIRST so
# a tunnel that dies mid-backlog still leaves the decisive evidence.
# Safe to re-run; each leg overwrites its own log under /tmp. The DONE
# sentinel records how many legs failed — "DONE failed=0" is the only
# all-clear (a flapping tunnel can fail every leg and still reach the
# end of this script).
cd "$(dirname "$0")/.."
set -x
failed=0
# 2-preset measure_presets legs now run each preset in its OWN subprocess
# (fresh jax init + compile, up to 1800s per child, plus settle gaps and
# repeats=3 timed legs), so the outer budget must cover BOTH children
run() { timeout 3900 "$@" || failed=$((failed+1)); }
# -- decisive legs first (VERDICT r3 items 2-3) --
run python scripts/measure_presets.py --presets ptb-transformer-large > /tmp/v_xl.log 2>&1
run python bench.py --preset resnet50-sync --profile /tmp/prof_r50 > /tmp/v_prof_r50.log 2>&1
run python bench.py --preset ptb-transformer-seq --profile /tmp/prof_tseq > /tmp/v_prof_tseq.log 2>&1
# -- serving numbers (VERDICT r3 item 8) --
run python bench.py --decode > /tmp/v_decode.log 2>&1
run python bench.py --decode --weights-dtype bf16 > /tmp/v_decode_bf16.log 2>&1
run python bench.py --decode --mixed > /tmp/v_decode_mixed.log 2>&1
run python bench.py --serve > /tmp/v_serve.log 2>&1
run python bench.py --serve --burst > /tmp/v_serve_burst.log 2>&1
run python bench.py --serve --weights-dtype bf16 > /tmp/v_serve_bf16.log 2>&1
run python bench.py --spec > /tmp/v_spec.log 2>&1
run python bench.py --serve --prefix-len 64 > /tmp/v_serve_prefix.log 2>&1
run python bench.py --load > /tmp/v_serve_load.log 2>&1
# -- sync-DP quantized/bucketed exchange A/B (each --dp run times BOTH
#    the raw and quantized staged-exchange legs on the same bucket plan;
#    the JSON line carries wire fraction + bytes drop + dynamics) --
run python bench.py --dp > /tmp/v_dp_int8.log 2>&1
run python bench.py --dp --quant bf16 > /tmp/v_dp_bf16.log 2>&1
# -- variant axes --
run python scripts/measure_presets.py --remat --presets resnet50-sync,ptb-transformer-seq > /tmp/v_remat.log 2>&1
run python scripts/measure_presets.py --set algo=zero-sync --presets mnist-easgd,cifar-vgg-sync > /tmp/v_zero.log 2>&1
run python scripts/measure_presets.py --set optimizer=adam --presets mnist-easgd > /tmp/v_adam.log 2>&1
run python scripts/measure_presets.py --set attn_impl=flash --presets ptb-transformer-seq > /tmp/v_flash.log 2>&1
run python scripts/measure_presets.py --presets ptb-transformer-pp --set pp_schedule=1f1b > /tmp/v_1f1b.log 2>&1
run python scripts/measure_presets.py --stem space_to_depth --presets resnet50-sync > /tmp/v_s2d_r50.log 2>&1
run python scripts/sweep_lenet.py > /tmp/v_sweep_lenet.log 2>&1
# -- elastic-membership churn soak (seeded kill/respawn every ~3s;
#    gates on obs dynamics + conformance with churn licensing; its
#    numbers are their own comparability mode — see bench_gate.py) --
run bash scripts/elastic_soak.sh 300 > /tmp/v_elastic_soak.log 2>&1
echo "DONE failed=$failed" > /tmp/tpu_backlog.done
