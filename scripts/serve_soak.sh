#!/usr/bin/env bash
# Multi-seed serving soak: seeded open-loop load runs gated on SLOs.
#
#   scripts/serve_soak.sh [N_SEEDS] [MAX_SECONDS]
#
# Each round drives `python -m mpit_tpu.loadgen` with a fresh seed
# (workload AND chaos schedule derive from it) into a throwaway journal
# dir, then gates the journals through
# `python -m mpit_tpu.obs slo --gate scripts/slo_smoke.json` and the
# live alert engine (`obs live --once` — runs are live-armed; any alert
# firing fails the round). Wall-clock
# is bounded like chaos_soak.sh: no new round starts once MAX_SECONDS
# (default 600) is spent. A failing seed prints its exact replay line —
# the run is a pure function of the seed, so the failure reproduces.
set -euo pipefail
cd "$(dirname "$0")/.."

N_SEEDS="${1:-5}"
MAX_SECONDS="${2:-600}"
START=$SECONDS
FAILED=0

for ((i = 0; i < N_SEEDS; i++)); do
  if ((SECONDS - START >= MAX_SECONDS)); then
    echo "serve_soak: budget of ${MAX_SECONDS}s spent after ${i} round(s); stopping" >&2
    break
  fi
  echo "=== serve soak round $((i + 1))/${N_SEEDS} (seed ${i}) ==="
  OUT="$(mktemp -d)"
  trap 'rm -rf "$OUT"' EXIT
  if ! env JAX_PLATFORMS=cpu python -m mpit_tpu.loadgen \
      --out "$OUT" --seed "$i" --requests 16 --rate 500 \
      --cancel-prob 0.1 --chaos-delay-p 0.05 --live; then
    FAILED=1
  elif ! env JAX_PLATFORMS=cpu python -m mpit_tpu.obs slo "$OUT" \
      --gate scripts/slo_smoke.json; then
    FAILED=1
  # live health gate: alert thresholds aligned with slo_smoke.json
  # (goodput_min 0.5 -> slo_target 0.5), so a run the SLO gate passes
  # must not burn-alert; any firing exits 1 and fails the round
  elif ! env JAX_PLATFORMS=cpu python -m mpit_tpu.obs live "$OUT" \
      --once --json --slo-target 0.5 --burn-threshold 1.0; then
    FAILED=1
  fi
  rm -rf "$OUT"
  trap - EXIT
  if ((FAILED)); then
    break
  fi
done

if ((FAILED)); then
  echo "serve_soak: FAILED at seed ${i} — replay with:" >&2
  echo "  python -m mpit_tpu.loadgen --out /tmp/serve_soak_${i} --seed ${i} --requests 16 --rate 500 --cancel-prob 0.1 --chaos-delay-p 0.05" >&2
  echo "  python -m mpit_tpu.obs slo /tmp/serve_soak_${i} --gate scripts/slo_smoke.json" >&2
  exit 1
fi
echo "serve_soak: OK"
