#!/usr/bin/env bash
# Multi-seed fleet soak: replica-kill + rolling-weight-refresh runs
# gated on the p99-moves-p50-doesn't pin.
#
#   scripts/fleet_soak.sh [N_SEEDS] [MAX_SECONDS]
#
# Each round runs the SAME seeded workload twice through
# `python -m mpit_tpu.fleet run` (3 replicas + 1 spare, controller
# armed):
#
#   clean  — no faults, rolling weight refreshes only;
#   chaos  — a replica SIGKILL (in-process kill flag) at a router
#            boundary, plus the same refreshes, so the kill lands while
#            versions are rolling.
#
# Both runs must audit zero-lost with monotone weight versions (the
# `run` exit code), then the round gates on:
#
#   `fleet pin --expect-kill`  — chaos e2e p50 within 3x of clean p50
#       (a kill may move the TAIL — the orphans pay a redispatch — but
#       must not move the MEDIAN), the kill demonstrably fired, and
#       nothing was lost;
#   `obs slo --gate fleet_smoke.json`   — the chaos run still clears
#       the serving floor (all requests finish, goodput >= 0.5);
#   `fleet audit`  — prints the postmortem naming the killed replica
#       and the redispatch count (and re-checks version monotonicity).
#
# Wall-clock is bounded like serve_soak.sh: no new round starts once
# MAX_SECONDS (default 600) is spent. A failing seed prints its exact
# replay lines — each run is a pure function of its flags.
set -euo pipefail
cd "$(dirname "$0")/.."

N_SEEDS="${1:-3}"
MAX_SECONDS="${2:-600}"
START=$SECONDS
FAILED=0

# rate 25 spreads the 16 arrivals over ~0.6s so the fleet is NOT
# saturated — the p50 pin is only an honest claim under non-saturating
# load (killing 1 of 3 replicas in a full-burst run cuts capacity for
# the whole run and rightly moves the median); --kill-after 30 lands
# the kill mid-run, while requests are in flight and versions rolling
RUN_FLAGS=(--requests 16 --rate 25 --replicas 3 --refresh-at 20,60 --quant bf16)
CHAOS_FLAGS=(--kill-after 30 --kill-rank 1 --spares 1 --controller)

for ((i = 0; i < N_SEEDS; i++)); do
  if ((SECONDS - START >= MAX_SECONDS)); then
    echo "fleet_soak: budget of ${MAX_SECONDS}s spent after ${i} round(s); stopping" >&2
    break
  fi
  echo "=== fleet soak round $((i + 1))/${N_SEEDS} (seed ${i}) ==="
  OUT="$(mktemp -d)"
  trap 'rm -rf "$OUT"' EXIT
  if ! env JAX_PLATFORMS=cpu python -m mpit_tpu.fleet run \
      --out "$OUT/clean" --seed "$i" "${RUN_FLAGS[@]}"; then
    FAILED=1
  elif ! env JAX_PLATFORMS=cpu python -m mpit_tpu.fleet run \
      --out "$OUT/chaos" --seed "$i" "${RUN_FLAGS[@]}" "${CHAOS_FLAGS[@]}"; then
    FAILED=1
  # --p50-factor 5 (vs the pin's default 3): thread-fleet medians on a
  # loaded CPU runner swing ~2x run-to-run; the LOST gate is the sharp
  # one, the factor only has to catch median collapse, not noise
  elif ! env JAX_PLATFORMS=cpu python -m mpit_tpu.fleet pin \
      "$OUT/clean" "$OUT/chaos" --expect-kill --p50-factor 5; then
    FAILED=1
  elif ! env JAX_PLATFORMS=cpu python -m mpit_tpu.obs slo "$OUT/chaos" \
      --gate scripts/fleet_smoke.json; then
    FAILED=1
  fi
  # the postmortem: names the killed replica, the redispatch count, and
  # the per-replica weight-version trail (exit 1 on loss/regression)
  if ! env JAX_PLATFORMS=cpu python -m mpit_tpu.fleet audit "$OUT/chaos"; then
    FAILED=1
  fi
  rm -rf "$OUT"
  trap - EXIT
  if ((FAILED)); then
    break
  fi
done

if ((FAILED)); then
  echo "fleet_soak: FAILED at seed ${i} — replay with:" >&2
  echo "  python -m mpit_tpu.fleet run --out /tmp/fleet_soak_${i}_clean --seed ${i} ${RUN_FLAGS[*]}" >&2
  echo "  python -m mpit_tpu.fleet run --out /tmp/fleet_soak_${i}_chaos --seed ${i} ${RUN_FLAGS[*]} ${CHAOS_FLAGS[*]}" >&2
  echo "  python -m mpit_tpu.fleet pin /tmp/fleet_soak_${i}_clean /tmp/fleet_soak_${i}_chaos --expect-kill" >&2
  exit 1
fi
echo "fleet_soak: OK"
