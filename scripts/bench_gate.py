#!/usr/bin/env python
"""Compare BENCH_*.json snapshots; flag regressions.

    python scripts/bench_gate.py [--strict] [--trend] [--threshold 0.10] [DIR]

The driver writes one ``BENCH_r<NN>.json`` per round (``n``, ``cmd``,
``rc``, ``tail``, ``parsed`` = the bench's JSON line). This gate reads
the two newest, matches them by metric, and flags movement beyond the
threshold in the direction that hurts:

- throughput (``value``) dropping;
- latency fields (``*_ms``) rising;
- ``goodput`` dropping;
- update-quality fields under ``dynamics`` (mnist-ps legs) moving in
  the direction that hurts: ``staleness_p99`` or ``elastic_dist_final``
  rising, ``norm_ratio`` drifting either way (its healthy value is an
  equilibrium, not a maximum). A field newly appearing from a zero/
  absent baseline warns too — quality cost showing up where there was
  none is exactly what an async-speedup "win" must disclose.

``--trend`` additionally scores the newest round against the BEST round
in the longest comparable history suffix (same metric, same platform
mode): five rounds each 3% slower never trip the pairwise 10% gate, but
the newest-vs-peak comparison catches the accumulated drift. The trend
pass uses the same ``--threshold`` and prints the series it scored.

Rounds measured on different platforms (a TPU round vs a dead-tunnel
CPU-smoke fallback, visible via ``platform``/``platform_note``) are
reported but never flagged — a 1000x "regression" between a TPU number
and a CPU number is a platform change, not a code change. The same
rule applies to the exchange configuration: rounds with different
quant/bucket/overlap modes (``dp_quant``/``dp_bucket_bytes``/
``dp_overlap`` on the collective legs, ``wire_format``/``wire_quant``
on the PS legs) are never scored against each other — an int8 round
"regressing" against a raw round is an A/B comparison, not a drift,
and it belongs in the bench's own ``vs_raw`` field. Membership-churn
runs (``elastic_churn`` truthy: ranks killed and respawned mid-run by
the elastic supervisor) are likewise their own comparability mode —
a soak that loses a rank every few seconds measures recovery cost,
not steady-state throughput, and must never be trended against a
stable-membership round.

Warn-only by default (exit 0 with warnings printed) because bench noise
must not block commits — scripts/lint.sh runs it that way (with
``--trend``). ``--strict`` exits 1 on flags (pairwise or trend) for CI
lanes that do gate on trajectory. Exit 2 on usage errors only; fewer
than two comparable snapshots is a clean pass (nothing to compare is
not a regression).

Stdlib-only and import-free of the package: safe in pre-commit hooks.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys


def _load_rounds(bench_dir: str) -> list:
    """BENCH_*.json files with a parsed metric, oldest -> newest (by the
    round counter ``n``, falling back to filename order)."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = data.get("parsed")
        if not isinstance(parsed, dict) or "metric" not in parsed:
            continue
        rounds.append((data.get("n", 0), path, parsed))
    rounds.sort(key=lambda r: r[0])
    return rounds


def _platform_mode(parsed: dict) -> str:
    """Comparable-measurement key: CPU-smoke fallbacks must not be
    scored against real-hardware rounds."""
    if parsed.get("platform_note"):
        return "cpu-smoke"
    return str(parsed.get("platform", "unknown"))


_EXCHANGE_KEYS = (
    # collective-exchange knobs (bench.py --dp / quantized trainers)
    "dp_quant", "dp_bucket_bytes", "dp_overlap",
    # PS socket-codec knobs (bench.py --preset mnist-ps)
    "wire_format", "wire_quant",
    # elastic-membership churn (scripts/elastic_soak.sh legs): a run
    # that kills/respawns ranks measures recovery, not steady state
    "elastic_churn",
    # sharded-PS topology: shard count and ring membership version both
    # change who serves which slice — a resharded round is a different
    # exchange, not a slower one
    "ps_shards", "ring_version",
    # serving-fleet shape (bench.py --load --fleet N): per-replica
    # goodput/latency scales with fleet size, and the routing policy
    # changes which replica absorbs the tail — different fleet, not a
    # regression
    "replica_count", "router_policy",
)


def _exchange_mode(parsed: dict) -> str:
    """Comparable-measurement key #2: rounds with different quant/
    bucket/overlap (or wire codec) modes are A/B variants of each
    other, not points on one trajectory — never score them pairwise."""
    return "/".join(str(parsed.get(k, "-")) for k in _EXCHANGE_KEYS)


_MS_KEY = re.compile(r"_ms$")


def compare(old: dict, new: dict, threshold: float) -> list:
    """Regression strings for one metric's old -> new movement."""
    flags = []

    def _num(d, k):
        v = d.get(k)
        return v if isinstance(v, (int, float)) and not isinstance(
            v, bool
        ) else None

    ov, nv = _num(old, "value"), _num(new, "value")
    if ov is not None and nv is not None and ov > 0:
        drop = (ov - nv) / ov
        if drop > threshold:
            flags.append(
                f"value {ov} -> {nv} ({drop:.1%} drop, "
                f"unit {new.get('unit', '?')})"
            )
    for k in sorted(set(old) & set(new)):
        if not _MS_KEY.search(k):
            continue
        ov, nv = _num(old, k), _num(new, k)
        if ov is None or nv is None or ov <= 0:
            continue
        rise = (nv - ov) / ov
        if rise > threshold:
            flags.append(f"{k} {ov} -> {nv} ({rise:.1%} rise)")
    ov, nv = _num(old, "goodput"), _num(new, "goodput")
    if ov is not None and nv is not None and ov > 0:
        drop = (ov - nv) / ov
        if drop > threshold:
            flags.append(f"goodput {ov} -> {nv} ({drop:.1%} drop)")
    od = old.get("dynamics") if isinstance(old.get("dynamics"), dict) else {}
    nd = new.get("dynamics") if isinstance(new.get("dynamics"), dict) else {}
    for k in ("staleness_p99", "elastic_dist_final"):
        ov, nv = _num(od, k), _num(nd, k)
        if nv is None:
            continue
        if ov is not None and ov > 0:
            rise = (nv - ov) / ov
            if rise > threshold:
                flags.append(f"dynamics.{k} {ov} -> {nv} "
                             f"({rise:.1%} rise)")
        elif nv > 0 and od:  # baseline had dynamics but this value was 0
            flags.append(f"dynamics.{k} 0 -> {nv} (quality cost "
                         "appeared from a zero baseline)")
    ov, nv = _num(od, "norm_ratio"), _num(nd, "norm_ratio")
    if ov is not None and nv is not None and ov > 0:
        drift = abs(nv - ov) / ov
        if drift > threshold:
            flags.append(f"dynamics.norm_ratio {ov} -> {nv} "
                         f"({drift:.1%} drift)")
    return flags


def comparable_series(rounds: list) -> list:
    """The longest suffix of ``rounds`` sharing the newest round's
    metric and platform mode — the history the trend pass scores."""
    if not rounds:
        return []
    newest = rounds[-1][2]
    key = (
        newest.get("metric"),
        _platform_mode(newest),
        _exchange_mode(newest),
    )
    series: list = []
    for item in reversed(rounds):
        parsed = item[2]
        if (
            parsed.get("metric"),
            _platform_mode(parsed),
            _exchange_mode(parsed),
        ) != key:
            break
        series.append(item)
    series.reverse()
    return series


def trend(rounds: list, threshold: float) -> tuple[list, str]:
    """(flag strings, series label) for newest-vs-best-of-history drift.

    Best means per-key best: max for ``value``/``goodput``, min for each
    ``*_ms`` — a single strong round anywhere in the comparable history
    is the standard the newest must stay within ``threshold`` of."""
    series = comparable_series(rounds)
    if len(series) < 3:
        # pairwise already covers 2; a 2-round "trend" would double-warn
        return [], ""
    newest_n, newest_path, newest = series[-1]
    history = [p for _, _, p in series[:-1]]
    label = (
        f"{os.path.basename(series[0][1])}.."
        f"{os.path.basename(newest_path)} "
        f"({len(series)} rounds, {newest.get('metric')}, "
        f"{_platform_mode(newest)})"
    )

    def _num(d, k):
        v = d.get(k)
        return v if isinstance(v, (int, float)) and not isinstance(
            v, bool
        ) else None

    flags = []
    for key, best_of in (("value", max), ("goodput", max)):
        vals = [
            (v, i) for i, p in enumerate(history)
            if (v := _num(p, key)) is not None and v > 0
        ]
        nv = _num(newest, key)
        if not vals or nv is None:
            continue
        best, at = best_of(vals)
        drop = (best - nv) / best
        if drop > threshold:
            flags.append(
                f"{key} peaked at {best} in "
                f"{os.path.basename(series[at][1])}, now {nv} "
                f"({drop:.1%} below peak)"
            )
    ms_keys = sorted(
        k for k in newest if _MS_KEY.search(k)
        if isinstance(newest.get(k), (int, float))
    )
    for k in ms_keys:
        vals = [
            (v, i) for i, p in enumerate(history)
            if (v := _num(p, k)) is not None and v > 0
        ]
        nv = _num(newest, k)
        if not vals or nv is None or nv <= 0:
            continue
        best, at = min(vals)
        rise = (nv - best) / best
        if rise > threshold:
            flags.append(
                f"{k} best was {best} in "
                f"{os.path.basename(series[at][1])}, now {nv} "
                f"({rise:.1%} above best)"
            )
    return flags, label


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    strict = "--strict" in argv
    if strict:
        argv.remove("--strict")
    trend_mode = "--trend" in argv
    if trend_mode:
        argv.remove("--trend")
    threshold = 0.10
    if "--threshold" in argv:
        i = argv.index("--threshold")
        try:
            threshold = float(argv[i + 1])
            del argv[i:i + 2]
        except (IndexError, ValueError):
            print("--threshold needs a number", file=sys.stderr)
            return 2
    if len(argv) > 1:
        print(__doc__.strip().splitlines()[2].strip(), file=sys.stderr)
        return 2
    bench_dir = argv[0] if argv else "."

    rounds = _load_rounds(bench_dir)
    if len(rounds) < 2:
        print(f"bench_gate: {len(rounds)} snapshot(s) under "
              f"{bench_dir} — nothing to compare")
        return 0
    (_, old_path, old), (_, new_path, new) = rounds[-2], rounds[-1]

    any_flags = False
    if old.get("metric") != new.get("metric"):
        print(f"bench_gate: metric changed "
              f"{old.get('metric')} -> {new.get('metric')} — skipping")
    elif (om := _platform_mode(old)) != (nm := _platform_mode(new)):
        print(f"bench_gate: platform changed {om} -> {nm} "
              f"({os.path.basename(old_path)} -> "
              f"{os.path.basename(new_path)}) — not comparable")
    elif (oe := _exchange_mode(old)) != (ne := _exchange_mode(new)):
        print(f"bench_gate: exchange mode changed {oe} -> {ne} "
              f"({os.path.basename(old_path)} -> "
              f"{os.path.basename(new_path)}) — not comparable "
              "(quant/bucket/overlap A/B, not a trajectory)")
    else:
        flags = compare(old, new, threshold)
        label = (f"{os.path.basename(old_path)} -> "
                 f"{os.path.basename(new_path)} "
                 f"({new.get('metric')}, {nm})")
        if not flags:
            print(f"bench_gate: OK {label}")
        for f in flags:
            print(f"bench_gate: WARNING {label}: {f}")
            any_flags = True

    if trend_mode:
        tflags, tlabel = trend(rounds, threshold)
        if tlabel and not tflags:
            print(f"bench_gate: trend OK {tlabel}")
        for f in tflags:
            print(f"bench_gate: TREND WARNING {tlabel}: {f}")
            any_flags = True

    return 1 if (strict and any_flags) else 0


if __name__ == "__main__":
    sys.exit(main())
