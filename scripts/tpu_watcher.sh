#!/bin/bash
# Tunnel watcher (round-4 first action, VERDICT r3 item 1): probe the
# hardware backend in a BOUNDED subprocess every ~5 min; the moment it
# answers, capture the driver-format bench JSON first (the official
# record three rounds of outages have blocked), then run the full
# measurement backlog. Touches /tmp/tpu_alive while hardware is usable so
# interactive sessions can avoid stacking host load on a live sweep
# (the 35% cifar-vgg outlier class, PERF.md).
cd "$(dirname "$0")/.."
log() { echo "$(date -Is) $*" >> /tmp/tpu_watcher.log; }
# single-instance guard: two watchers would double-run the backlog and
# stack host load on the live window they exist to protect
if [ -f /tmp/tpu_watcher.pid ] && kill -0 "$(cat /tmp/tpu_watcher.pid)" 2>/dev/null; then
  log "watcher already running (pid $(cat /tmp/tpu_watcher.pid)) — exiting"
  exit 0
fi
echo $$ > /tmp/tpu_watcher.pid
trap 'rm -f /tmp/tpu_alive /tmp/tpu_watcher.pid' EXIT
log "watcher start (pid $$)"
bench_json_good() {
  # a captured record counts only if it is valid JSON from a TPU run
  python - <<'EOF' >/dev/null 2>&1
import json
d = json.load(open("/tmp/bench_tpu.json"))
assert d.get("platform") not in (None, "cpu")
EOF
}
while true; do
  if timeout 180 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    touch /tmp/tpu_alive
    log "tunnel ALIVE"
    if bench_json_good; then
      log "bench JSON already captured — skipping straight to backlog"
    else
      log "running bench.py (official record)"
      # temp + mv: a tunnel dying mid-bench must not destroy an earlier
      # successful capture with a truncating redirect
      if timeout 1800 python bench.py > /tmp/bench_tpu.json.part 2>/tmp/bench_tpu.err \
          && [ -s /tmp/bench_tpu.json.part ]; then
        mv /tmp/bench_tpu.json.part /tmp/bench_tpu.json
      fi
      log "bench.py done: $(head -c 300 /tmp/bench_tpu.json 2>/dev/null)"
    fi
    bash scripts/tpu_backlog.sh >> /tmp/tpu_watcher.log 2>&1
    log "backlog sentinel: $(cat /tmp/tpu_backlog.done 2>/dev/null)"
    rm -f /tmp/tpu_alive
    # keep watching: a later window can re-run any failed legs
    if bench_json_good && grep -q "failed=0" /tmp/tpu_backlog.done 2>/dev/null; then
      log "all legs clean — watcher exiting"
      break
    fi
  else
    log "tunnel dead"
  fi
  sleep 300
done
