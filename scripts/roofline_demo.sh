#!/usr/bin/env bash
# One-command roofline demo (docs/OBSERVABILITY.md, *Roofline*):
#
#   scripts/roofline_demo.sh [OUT_DIR] [MAX_SECONDS]
#
# Runs a small multi-process PS training (1 server, 2 clients over real
# SocketTransport) with obs armed, then joins the per-rank journals into
# the compute/wire/idle/overhead attribution:
#
#   OUT_DIR/obs_rank{0,1,2}.jsonl   per-rank event journals
#   stdout                          per-rank roofline table + run line
#
# Wall-clock is bounded: the training run is killed at MAX_SECONDS
# (default 120) rather than hanging the shell.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT_DIR="${1:-/tmp/mpit_roofline_demo}"
MAX_SECONDS="${2:-120}"

rm -rf "$OUT_DIR"
mkdir -p "$OUT_DIR"

echo "=== roofline_demo: 3-rank easgd run, journals -> $OUT_DIR ==="
env JAX_PLATFORMS=cpu \
    MPIT_OBS_DIR="$OUT_DIR" \
    timeout -k 10 "$MAX_SECONDS" \
    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
    --model mlp --steps 16 --train-size 256 --algo ps-easgd

echo "=== roofline_demo: per-rank attribution ==="
python -m mpit_tpu.obs roofline "$OUT_DIR"

echo "roofline_demo: OK — full report: python -m mpit_tpu.obs roofline $OUT_DIR --json"
