"""Collect the honest preset benchmark table on the live backend.

Runs every benchmarkable BASELINE preset serially through ``bench.bench_preset``
(the same harness ``bench.py`` uses), printing one JSON row per preset and a
final markdown table for docs/PERF.md. Optional variants per preset via flags:

  --input-dtype bf16     stage float inputs as bfloat16 (data.cast_input_dtype)
  --presets a,b,c        subset (default: all)
  --stem space_to_depth  stem variant for stem-capable presets (resnet50,
                         alexnet); others ignore it
  --remat                rematerialize blocks (resnet50/transformer presets)
  --set key=value        generic TrainConfig override, repeatable — the
                         channel for every other variant axis, e.g.
                         --set attn_impl=flash --set seq_impl=ulysses
                         --set algo=zero-sync --set pp_schedule=1f1b
                         (values cast by the field's type; unknown keys
                         fail at startup)

Keep the host otherwise idle while this runs — the box has one CPU core and
the timing legs dispatch from it.
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import bench  # noqa: E402


def main():
    argv = sys.argv[1:]

    def flag(name, default=None):
        """`name VALUE` from argv; usage-errors like bench.py's flag_arg
        when the value is missing or is another flag."""
        if name not in argv:
            return default
        i = argv.index(name) + 1
        if i >= len(argv) or argv[i].startswith("--"):
            print(f"{name} requires an argument", file=sys.stderr)
            raise SystemExit(2)
        return argv[i]

    from mpit_tpu.data import INPUT_DTYPES

    input_dtype = flag("--input-dtype", "float32")
    if input_dtype not in INPUT_DTYPES:  # fail at startup, not per-preset
        print(
            f"--input-dtype must be one of {INPUT_DTYPES}, "
            f"got {input_dtype!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)
    names = flag("--presets")
    names = names.split(",") if names else list(bench.ALL_BENCH_PRESETS)
    stem = flag("--stem")
    if stem is not None and stem not in ("conv", "space_to_depth"):
        print(
            f"--stem must be conv or space_to_depth, got {stem!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)

    from mpit_tpu.models import REMAT_MODELS, STEM_MODELS
    from mpit_tpu.utils.config import TrainConfig

    remat = "--remat" in argv

    # --set key=value (repeatable): generic TrainConfig overrides, cast
    # by the field's ANNOTATION (type(default) lies for Optional fields
    # whose default is None — alpha, client_timeout); every bad input
    # fails here, not 25 minutes into the serial sweep
    import dataclasses

    _CAST = {
        "int": int, "float": float, "str": str,
        "Optional[int]": int, "Optional[float]": float,
        "Optional[str]": str,
    }
    field_ann = {
        f.name: str(f.type) for f in dataclasses.fields(TrainConfig)
    }
    overrides = {}
    for i, a in enumerate(argv):
        if a != "--set":
            continue
        if i + 1 >= len(argv) or "=" not in argv[i + 1]:
            print("--set requires key=value", file=sys.stderr)
            raise SystemExit(2)
        key, _, val = argv[i + 1].partition("=")
        if key not in field_ann:
            print(f"--set: unknown config field {key!r}", file=sys.stderr)
            raise SystemExit(2)
        if key == "input_dtype":
            # bench_preset stages data via its own input_dtype parameter,
            # not cfg — an override here would silently measure float32
            print(
                "--set input_dtype=... would be a silent no-op; use "
                "--input-dtype",
                file=sys.stderr,
            )
            raise SystemExit(2)
        ann = field_ann[key]
        if ann == "bool":
            if val.lower() not in ("0", "1", "true", "false"):
                print(
                    f"--set {key}: bool wants true/false/1/0, "
                    f"got {val!r}",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            overrides[key] = val.lower() in ("1", "true")
        else:
            try:
                overrides[key] = _CAST.get(ann, str)(val)
            except ValueError:
                print(
                    f"--set {key}: cannot cast {val!r} to {ann}",
                    file=sys.stderr,
                )
                raise SystemExit(2)

    def variant_kw(name):
        """Pass stem/remat only to presets whose model takes them."""
        model = TrainConfig().apply_preset(name).model.lower()
        kw = {}
        if stem is not None and model in STEM_MODELS:
            kw["stem"] = stem
        if remat and model in REMAT_MODELS:
            kw["remat"] = True
        return kw

    rows = []
    for name in names:
        try:
            res = bench.bench_preset(
                name, input_dtype=input_dtype,
                overrides=overrides or None, **variant_kw(name)
            )
        except Exception as e:  # keep the sweep alive past one bad preset
            print(json.dumps({"preset": name, "error": repr(e)}), flush=True)
            continue
        row = {
            "preset": name,
            "samples_per_sec_per_chip": round(
                res["samples_per_sec_per_chip"], 1
            ),
            "mfu": res.get("mfu"),
            "tau": res.get("tau"),
            "per_worker_batch": res.get(
                "per_worker_batch", res.get("per_client_batch")
            ),
            "timed_seconds": res.get("timed_seconds"),
            "input_dtype": input_dtype,
            # variant rows must be distinguishable from baseline rows
            **({"overrides": overrides} if overrides else {}),
            **{k: res[k] for k in ("accuracy", "stem") if k in res},
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    if overrides:
        print(f"\nvariant: {json.dumps(overrides)}")
    print("\n| Preset | samples/s/chip | MFU |")
    print("|---|---|---|")
    for r in rows:
        mfu = f"{100 * r['mfu']:.1f}%" if r.get("mfu") else "—"
        print(
            f"| {r['preset']} | {r['samples_per_sec_per_chip']:,.0f} | {mfu} |"
        )


if __name__ == "__main__":
    main()
