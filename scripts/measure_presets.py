"""Collect the honest preset benchmark table on the live backend.

Runs every benchmarkable BASELINE preset through ``bench.bench_preset``
(the same harness ``bench.py`` uses), printing one JSON row per preset and a
final markdown table for docs/PERF.md. Optional variants per preset via flags:

  --input-dtype bf16     stage float inputs as bfloat16 (data.cast_input_dtype)
  --presets a,b,c        subset (default: all)
  --stem space_to_depth  stem variant for stem-capable presets (resnet50,
                         alexnet); others ignore it
  --remat                rematerialize blocks (resnet50/transformer presets)
  --set key=value        generic TrainConfig override, repeatable — the
                         channel for every other variant axis, e.g.
                         --set attn_impl=flash --set seq_impl=ulysses
                         --set algo=zero-sync --set pp_schedule=1f1b
                         (values cast by the field's type; unknown keys
                         fail at startup)
  --repeats N            timed-leg repeats per preset (default 3): the row
                         reports the MEDIAN rate plus leg-to-leg spread,
                         and flags spread >10% (host-interference class)
  --no-isolate           run presets in-process (old behavior, debugging)

Variance discipline (VERDICT r3 weak-item 2): by default every preset runs
in its OWN subprocess with a settle gap between presets, so one preset's
teardown (host-side frees, tunnel traffic) cannot leak into the next
preset's timed legs on this one-core box — the 68.5k-vs-105k cifar-vgg
outlier class. Rows land in docs/measurements/sweeps.jsonl (timestamped)
and baseline rows on real hardware refresh docs/measurements/LATEST.json,
the evidence trail bench.py's CPU fallback reports.

Keep the host otherwise idle while this runs — the box has one CPU core and
the timing legs dispatch from it.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import bench  # noqa: E402

SETTLE_SECONDS = 3.0
CHILD_TIMEOUT = 1800


def parse_flags(argv):
    def flag(name, default=None):
        """`name VALUE` from argv; usage-errors like bench.py's flag_arg
        when the value is missing or is another flag."""
        if name not in argv:
            return default
        i = argv.index(name) + 1
        if i >= len(argv) or argv[i].startswith("--"):
            print(f"{name} requires an argument", file=sys.stderr)
            raise SystemExit(2)
        return argv[i]

    from mpit_tpu.data import INPUT_DTYPES

    input_dtype = flag("--input-dtype", "float32")
    if input_dtype not in INPUT_DTYPES:  # fail at startup, not per-preset
        print(
            f"--input-dtype must be one of {INPUT_DTYPES}, "
            f"got {input_dtype!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)
    names = flag("--presets")
    names = names.split(",") if names else list(bench.ALL_BENCH_PRESETS)
    stem = flag("--stem")
    if stem is not None and stem not in ("conv", "space_to_depth"):
        print(
            f"--stem must be conv or space_to_depth, got {stem!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)
    try:
        repeats = int(flag("--repeats", "3"))
    except ValueError:
        print("--repeats wants an int", file=sys.stderr)
        raise SystemExit(2)

    from mpit_tpu.utils.config import TrainConfig

    # --set key=value (repeatable): generic TrainConfig overrides, cast
    # by the field's ANNOTATION (type(default) lies for Optional fields
    # whose default is None — alpha, client_timeout); every bad input
    # fails here, not 25 minutes into the serial sweep
    import dataclasses

    _CAST = {
        "int": int, "float": float, "str": str,
        "Optional[int]": int, "Optional[float]": float,
        "Optional[str]": str,
    }
    field_ann = {
        f.name: str(f.type) for f in dataclasses.fields(TrainConfig)
    }
    overrides = {}
    for i, a in enumerate(argv):
        if a != "--set":
            continue
        if i + 1 >= len(argv) or "=" not in argv[i + 1]:
            print("--set requires key=value", file=sys.stderr)
            raise SystemExit(2)
        key, _, val = argv[i + 1].partition("=")
        if key not in field_ann:
            print(f"--set: unknown config field {key!r}", file=sys.stderr)
            raise SystemExit(2)
        if key == "input_dtype":
            # bench_preset stages data via its own input_dtype parameter,
            # not cfg — an override here would silently measure float32
            print(
                "--set input_dtype=... would be a silent no-op; use "
                "--input-dtype",
                file=sys.stderr,
            )
            raise SystemExit(2)
        ann = field_ann[key]
        if ann == "bool":
            if val.lower() not in ("0", "1", "true", "false"):
                print(
                    f"--set {key}: bool wants true/false/1/0, "
                    f"got {val!r}",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            overrides[key] = val.lower() in ("1", "true")
        else:
            try:
                overrides[key] = _CAST.get(ann, str)(val)
            except ValueError:
                print(
                    f"--set {key}: cannot cast {val!r} to {ann}",
                    file=sys.stderr,
                )
                raise SystemExit(2)
    return dict(
        input_dtype=input_dtype, names=names, stem=stem,
        remat="--remat" in argv, overrides=overrides, repeats=repeats,
        isolate="--no-isolate" not in argv, child="--child" in argv,
    )


def measure_one(name, opts):
    """One preset through the shared harness; returns the JSONL row."""
    from mpit_tpu.models import REMAT_MODELS, STEM_MODELS
    from mpit_tpu.utils.config import TrainConfig

    model = TrainConfig().apply_preset(name).model.lower()
    kw = {}
    if opts["stem"] is not None and model in STEM_MODELS:
        kw["stem"] = opts["stem"]
    if opts["remat"] and model in REMAT_MODELS:
        kw["remat"] = True
    res = bench.bench_preset(
        name, input_dtype=opts["input_dtype"],
        overrides=opts["overrides"] or None, repeats=opts["repeats"],
        # wiring-test hook (inherited by isolated children via env): tiny
        # shapes so the sweep's plumbing is testable on the CPU backend,
        # where full-size conv compiles take minutes
        cpu_smoke=os.environ.get("MPIT_MEASURE_SMOKE") == "1", **kw
    )
    return {
        "preset": name,
        "samples_per_sec_per_chip": round(
            res["samples_per_sec_per_chip"], 1
        ),
        "mfu": res.get("mfu"),
        "tau": res.get("tau"),
        "per_worker_batch": res.get(
            "per_worker_batch", res.get("per_client_batch")
        ),
        "timed_seconds": res.get("timed_seconds"),
        "input_dtype": opts["input_dtype"],
        "platform": res.get("platform"),
        **{k: res[k] for k in ("repeats", "spread", "variance_flagged")
           if res.get(k) is not None},
        # variant rows must be distinguishable from baseline rows
        **({"overrides": opts["overrides"]} if opts["overrides"] else {}),
        **{k: res[k] for k in ("accuracy", "stem") if k in res},
    }


def run_isolated(name, argv):
    """Re-exec this script for ONE preset in a fresh subprocess (its own
    jax runtime, its own teardown) and parse the row off its stdout."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--presets", name]
    skip_next = False
    for i, a in enumerate(argv):  # pass every flag through except --presets
        if skip_next:
            skip_next = False
            continue
        if a == "--presets":
            skip_next = True
            continue
        cmd.append(a)
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=CHILD_TIMEOUT
        )
    except subprocess.TimeoutExpired:
        return {"preset": name, "error": f"timeout after {CHILD_TIMEOUT}s"}
    for line in proc.stdout.splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and row.get("preset") == name:
            return row
    return {
        "preset": name,
        "error": f"child rc={proc.returncode}, no row "
                 f"(stderr tail: {proc.stderr[-300:]!r})",
    }


def archive(rows, opts):
    """Append timestamped rows to sweeps.jsonl; refresh LATEST.json for
    baseline rows measured on real hardware."""
    ts = time.strftime("%Y-%m-%dT%H:%M:%S")
    path = os.path.join(bench._MEASUREMENTS, "sweeps.jsonl")
    try:
        os.makedirs(bench._MEASUREMENTS, exist_ok=True)
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps({"ts": ts, **row}) + "\n")
    except Exception as e:
        print(f"archive failed: {e!r}", file=sys.stderr)
    baseline = (
        not opts["overrides"] and opts["stem"] is None
        and not opts["remat"] and opts["input_dtype"] == "float32"
    )
    if not baseline:
        return
    for row in rows:
        if "error" in row or row.get("platform") in (None, "cpu"):
            continue
        if row.get("variance_flagged"):
            continue  # an outlier row must not become the evidence trail
        bench.update_latest_measurement(row["preset"], {
            "samples_per_sec_per_chip": row["samples_per_sec_per_chip"],
            **({"mfu": row["mfu"]} if row.get("mfu") else {}),
            **({"spread": row["spread"]}
               if row.get("spread") is not None else {}),
            "source": "sweeps.jsonl",
        })


def main():
    # a sitecustomize-registered hardware backend wins over JAX_PLATFORMS
    # set after interpreter start; re-pin through the config API so
    # CPU-pinned runs of this sweep (wiring tests, smoke) actually land
    # on CPU instead of hanging on a dead tunnel (bench.py's recipe)
    bench._honor_platform_env()
    argv = sys.argv[1:]
    opts = parse_flags(argv)

    if opts["child"]:  # worker mode: one preset, one row, no table
        for name in opts["names"]:
            row = measure_one(name, opts)
            print(json.dumps(row), flush=True)
        return

    rows = []
    for i, name in enumerate(opts["names"]):
        if i and opts["isolate"]:
            time.sleep(SETTLE_SECONDS)  # let the previous child's
            # teardown (frees, tunnel traffic) drain before timing again
        if opts["isolate"]:
            row = run_isolated(name, argv)
        else:
            try:
                row = measure_one(name, opts)
            except Exception as e:  # keep the sweep alive past one preset
                row = {"preset": name, "error": repr(e)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    if os.environ.get("MPIT_MEASURE_SMOKE") != "1":  # wiring runs are
        # not measurements — keep them out of the archive
        archive([r for r in rows if "error" not in r], opts)

    if opts["overrides"]:
        print(f"\nvariant: {json.dumps(opts['overrides'])}")
    print("\n| Preset | samples/s/chip | MFU | spread |")
    print("|---|---|---|---|")
    for r in rows:
        if "error" in r:
            print(f"| {r['preset']} | FAILED | — | — |")
            continue
        mfu = f"{100 * r['mfu']:.1f}%" if r.get("mfu") else "—"
        spread = (
            f"{100 * r['spread']:.1f}%"
            + (" ⚠" if r.get("variance_flagged") else "")
            if r.get("spread") is not None else "—"
        )
        print(
            f"| {r['preset']} | {r['samples_per_sec_per_chip']:,.0f} "
            f"| {mfu} | {spread} |"
        )


if __name__ == "__main__":
    main()
