"""Collect the honest preset benchmark table on the live backend.

Runs every benchmarkable BASELINE preset serially through ``bench.bench_preset``
(the same harness ``bench.py`` uses), printing one JSON row per preset and a
final markdown table for docs/PERF.md. Optional variants per preset via flags:

  --input-dtype bf16     stage float inputs as bfloat16 (data.cast_input_dtype)
  --presets a,b,c        subset (default: all)
  --stem space_to_depth  stem variant for stem-capable presets (resnet50,
                         alexnet); others ignore it
  --remat                rematerialize blocks (resnet50/transformer presets)

Keep the host otherwise idle while this runs — the box has one CPU core and
the timing legs dispatch from it.
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import bench  # noqa: E402


def main():
    argv = sys.argv[1:]

    def flag(name, default=None):
        """`name VALUE` from argv; usage-errors like bench.py's flag_arg
        when the value is missing or is another flag."""
        if name not in argv:
            return default
        i = argv.index(name) + 1
        if i >= len(argv) or argv[i].startswith("--"):
            print(f"{name} requires an argument", file=sys.stderr)
            raise SystemExit(2)
        return argv[i]

    from mpit_tpu.data import INPUT_DTYPES

    input_dtype = flag("--input-dtype", "float32")
    if input_dtype not in INPUT_DTYPES:  # fail at startup, not per-preset
        print(
            f"--input-dtype must be one of {INPUT_DTYPES}, "
            f"got {input_dtype!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)
    names = flag("--presets")
    names = names.split(",") if names else list(bench.ALL_BENCH_PRESETS)
    stem = flag("--stem")
    if stem is not None and stem not in ("conv", "space_to_depth"):
        print(
            f"--stem must be conv or space_to_depth, got {stem!r}",
            file=sys.stderr,
        )
        raise SystemExit(2)

    from mpit_tpu.models import REMAT_MODELS, STEM_MODELS
    from mpit_tpu.utils.config import TrainConfig

    remat = "--remat" in argv

    def variant_kw(name):
        """Pass stem/remat only to presets whose model takes them."""
        model = TrainConfig().apply_preset(name).model.lower()
        kw = {}
        if stem is not None and model in STEM_MODELS:
            kw["stem"] = stem
        if remat and model in REMAT_MODELS:
            kw["remat"] = True
        return kw

    rows = []
    for name in names:
        try:
            res = bench.bench_preset(
                name, input_dtype=input_dtype, **variant_kw(name)
            )
        except Exception as e:  # keep the sweep alive past one bad preset
            print(json.dumps({"preset": name, "error": repr(e)}), flush=True)
            continue
        row = {
            "preset": name,
            "samples_per_sec_per_chip": round(
                res["samples_per_sec_per_chip"], 1
            ),
            "mfu": res.get("mfu"),
            "tau": res.get("tau"),
            "per_worker_batch": res.get(
                "per_worker_batch", res.get("per_client_batch")
            ),
            "timed_seconds": res.get("timed_seconds"),
            "input_dtype": input_dtype,
            **{k: res[k] for k in ("accuracy", "stem") if k in res},
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    print("\n| Preset | samples/s/chip | MFU |")
    print("|---|---|---|")
    for r in rows:
        mfu = f"{100 * r['mfu']:.1f}%" if r.get("mfu") else "—"
        print(
            f"| {r['preset']} | {r['samples_per_sec_per_chip']:,.0f} | {mfu} |"
        )


if __name__ == "__main__":
    main()
