#!/usr/bin/env bash
# Distributed-correctness lint gate.
#
#   scripts/lint.sh                 # fail on findings not in the baseline
#   scripts/lint.sh --update        # accept the current findings as baseline
#   scripts/lint.sh --fix           # rewrite fixable MPT002 sites, then gate
#   scripts/lint.sh path/to/file.py # lint specific paths (vs the baseline)
#
# The default run is five gates behind the one baseline:
#   1. the static lint (MPT001-008, MPT012) + protocol model check
#      (MPT009-011);
#   2. an explicit `mcheck` pass, so the exhaustive state counts land in
#      the CI log even when everything is green;
#   3. smoke `conform` passes over the checked-in good-run journals —
#      the trace-conformance path exercised on every lint: the chaos
#      fixture covers TC201-203, the dynamics fixture carries
#      param_version records so TC204 runs non-vacuously;
#   4. live-snapshot schema validation over the checked-in golden
#      (tests/fixtures/live — the `obs live --validate` contract);
#   5. the training-dynamics gate over the checked-in dynamics golden
#      (tests/fixtures/dynamics/good_run vs scripts/dynamics_smoke.json
#      — the `obs dynamics --gate` contract);
#   6. a jax-free import probe of the shared quant kernels
#      (mpit_tpu/quant.py + the transport.wire re-exports) — the host
#      wire path must never grow a backend dependency;
#   7. the black-box post-mortem contract over the checked-in golden
#      (tests/fixtures/blackbox: 3-rank run, rank 2 SIGKILLed) — exit
#      codes pinned: the incident fixture must exit 1 naming rank 2 as
#      first-mover, an empty dir must exit 2.
# The whole default run is bounded to < 15 s wall-clock
# (tests/test_lint_gate.py enforces it).
#
# Exit codes: 0 clean vs baseline, 1 new findings, 2 usage error.
# The linter parses, never imports, the scanned code and initializes no
# jax backend — safe for pre-commit hooks.
set -euo pipefail

cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--update" ]]; then
    shift
    exec python -m mpit_tpu.analysis --write-baseline "${@:-mpit_tpu/}"
fi

if [[ "${1:-}" == "--fix" ]]; then
    shift
    exec python -m mpit_tpu.analysis --fix "${@:-mpit_tpu/}"
fi

python -m mpit_tpu.analysis "${@:-mpit_tpu/}"

# explicit-path gates only make sense for the default whole-package run
if [[ $# -eq 0 ]]; then
    python -m mpit_tpu.analysis mcheck
    # one extraction, two audits: the chaos fixture covers TC201-203
    # under faults, the dynamics fixture carries param_version records
    # so TC204 (version monotonicity) runs non-vacuously
    python -m mpit_tpu.analysis conform \
        tests/fixtures/conformance/good_run tests/fixtures/dynamics/good_run
    # the live-snapshot schema contract, gated on the checked-in golden
    python -m mpit_tpu.obs live tests/fixtures/live --validate
    # the update-quality contract, gated on the same dynamics golden
    python -m mpit_tpu.obs dynamics tests/fixtures/dynamics/good_run \
        --gate scripts/dynamics_smoke.json
    # the shared quant kernels must stay importable WITHOUT a jax
    # backend (the host wire path depends on it; the jnp half is lazy) —
    # and the transport re-exports the MPT007 coverage rides on must
    # resolve from the numpy side alone
    python - <<'EOF'
import importlib.util, sys
import numpy as np
sys.modules["jax"] = None  # poison: any jax import below fails loudly
spec = importlib.util.spec_from_file_location(
    "quant_probe", "mpit_tpu/quant.py"
)
quant = importlib.util.module_from_spec(spec)
sys.modules["quant_probe"] = quant  # dataclass machinery resolves via here
spec.loader.exec_module(quant)  # must not touch jax (the jnp half is lazy)
q = quant.quantize(np.ones(8, np.float32), "int8")
out = quant.dequantize(q)
assert out.shape == (8,) and out.dtype == np.float32
EOF
    # the post-mortem contract, gated on the checked-in incident golden
    # (exit codes are part of the CLI contract: 1 = incident found,
    # 2 = no dumps; one python process drives obs_main for both runs).
    # The package __init__s are stubbed out: like gate 6, this doubles
    # as a probe that the post-mortem path stays stdlib-only — an
    # incident box must never need a jax backend to read the black box
    python - <<'EOF'
import importlib, io, json, os, sys, tempfile, types
from contextlib import redirect_stderr, redirect_stdout

for name, path in (("mpit_tpu", "mpit_tpu"), ("mpit_tpu.obs", "mpit_tpu/obs")):
    stub = types.ModuleType(name)
    stub.__path__ = [path]
    sys.modules[name] = stub
obs_main = importlib.import_module("mpit_tpu.obs.__main__").main

buf = io.StringIO()
with redirect_stdout(buf):
    rc = obs_main(["postmortem", "tests/fixtures/blackbox", "--json"])
assert rc == 1, f"postmortem gate: incident fixture exited {rc} (want 1)"
rep = json.loads(buf.getvalue())
assert rep["verdict"] == "incident", rep["verdict"]
assert rep["first_mover"]["rank"] == 2, rep["first_mover"]
assert "2" in rep["exchanges"], sorted(rep["exchanges"])
empty = tempfile.mkdtemp()
try:
    with redirect_stderr(io.StringIO()):
        rc = obs_main(["postmortem", empty, "--json"])
finally:
    os.rmdir(empty)
assert rc == 2, f"postmortem gate: empty dir exited {rc} (want 2)"
print(
    "postmortem gate: first-mover rank 2, "
    f"{len(rep['exchanges']['2']['pushes'])} reconstructed round(s), "
    "exit codes 1/2 pinned — ok"
)
EOF
    # warn-only: bench trajectory drift should be SEEN at lint time, but
    # bench noise must never block a commit (--strict exists for CI)
    python scripts/bench_gate.py --trend || true
fi
