#!/usr/bin/env bash
# Distributed-correctness lint gate.
#
#   scripts/lint.sh                 # fail on findings not in the baseline
#   scripts/lint.sh --update        # accept the current findings as baseline
#   scripts/lint.sh --fix           # rewrite fixable MPT002 sites, then gate
#   scripts/lint.sh path/to/file.py # lint specific paths (vs the baseline)
#
# The default run is five gates behind the one baseline:
#   1. the static lint (MPT001-008, MPT012) + protocol model check
#      (MPT009-011);
#   2. an explicit `mcheck` pass, so the exhaustive state counts land in
#      the CI log even when everything is green;
#   3. smoke `conform` passes over the checked-in good-run journals —
#      the trace-conformance path exercised on every lint: the chaos
#      fixture covers TC201-203, the dynamics fixture carries
#      param_version records so TC204 runs non-vacuously;
#   4. live-snapshot schema validation over the checked-in golden
#      (tests/fixtures/live — the `obs live --validate` contract);
#   5. the training-dynamics gate over the checked-in dynamics golden
#      (tests/fixtures/dynamics/good_run vs scripts/dynamics_smoke.json
#      — the `obs dynamics --gate` contract);
#   6. a jax-free import probe of the shared quant kernels
#      (mpit_tpu/quant.py + the transport.wire re-exports) — the host
#      wire path must never grow a backend dependency.
# The whole default run is bounded to < 15 s wall-clock
# (tests/test_lint_gate.py enforces it).
#
# Exit codes: 0 clean vs baseline, 1 new findings, 2 usage error.
# The linter parses, never imports, the scanned code and initializes no
# jax backend — safe for pre-commit hooks.
set -euo pipefail

cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--update" ]]; then
    shift
    exec python -m mpit_tpu.analysis --write-baseline "${@:-mpit_tpu/}"
fi

if [[ "${1:-}" == "--fix" ]]; then
    shift
    exec python -m mpit_tpu.analysis --fix "${@:-mpit_tpu/}"
fi

python -m mpit_tpu.analysis "${@:-mpit_tpu/}"

# explicit-path gates only make sense for the default whole-package run
if [[ $# -eq 0 ]]; then
    python -m mpit_tpu.analysis mcheck
    # one extraction, two audits: the chaos fixture covers TC201-203
    # under faults, the dynamics fixture carries param_version records
    # so TC204 (version monotonicity) runs non-vacuously
    python -m mpit_tpu.analysis conform \
        tests/fixtures/conformance/good_run tests/fixtures/dynamics/good_run
    # the live-snapshot schema contract, gated on the checked-in golden
    python -m mpit_tpu.obs live tests/fixtures/live --validate
    # the update-quality contract, gated on the same dynamics golden
    python -m mpit_tpu.obs dynamics tests/fixtures/dynamics/good_run \
        --gate scripts/dynamics_smoke.json
    # the shared quant kernels must stay importable WITHOUT a jax
    # backend (the host wire path depends on it; the jnp half is lazy) —
    # and the transport re-exports the MPT007 coverage rides on must
    # resolve from the numpy side alone
    python - <<'EOF'
import importlib.util, sys
import numpy as np
sys.modules["jax"] = None  # poison: any jax import below fails loudly
spec = importlib.util.spec_from_file_location(
    "quant_probe", "mpit_tpu/quant.py"
)
quant = importlib.util.module_from_spec(spec)
sys.modules["quant_probe"] = quant  # dataclass machinery resolves via here
spec.loader.exec_module(quant)  # must not touch jax (the jnp half is lazy)
q = quant.quantize(np.ones(8, np.float32), "int8")
out = quant.dequantize(q)
assert out.shape == (8,) and out.dtype == np.float32
EOF
    # warn-only: bench trajectory drift should be SEEN at lint time, but
    # bench noise must never block a commit (--strict exists for CI)
    python scripts/bench_gate.py --trend || true
fi
