#!/usr/bin/env bash
# Distributed-correctness lint gate.
#
#   scripts/lint.sh                 # fail on findings not in the baseline
#   scripts/lint.sh --update        # accept the current findings as baseline
#   scripts/lint.sh --fix           # rewrite fixable MPT002 sites, then gate
#   scripts/lint.sh path/to/file.py # lint specific paths (vs the baseline)
#
# Exit codes: 0 clean vs baseline, 1 new findings, 2 usage error.
# The linter parses, never imports, the scanned code and initializes no
# jax backend — safe for pre-commit hooks.
set -euo pipefail

cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--update" ]]; then
    shift
    exec python -m mpit_tpu.analysis --write-baseline "${@:-mpit_tpu/}"
fi

if [[ "${1:-}" == "--fix" ]]; then
    shift
    exec python -m mpit_tpu.analysis --fix "${@:-mpit_tpu/}"
fi

exec python -m mpit_tpu.analysis "${@:-mpit_tpu/}"
