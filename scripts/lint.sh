#!/usr/bin/env bash
# Distributed-correctness lint gate.
#
#   scripts/lint.sh                 # fail on findings not in the baseline
#   scripts/lint.sh --strict        # CI mode: bench trend drift blocks too
#   scripts/lint.sh --update        # accept the current findings as baseline
#   scripts/lint.sh --fix           # rewrite fixable MPT002 sites, then gate
#   scripts/lint.sh path/to/file.py # lint specific paths (vs the baseline)
#   scripts/lint.sh --fix --only MPT013,MPT015 path.py
#                                   # everything after --fix passes through,
#                                   # so one rule iterates without the full
#                                   # pass (--only also works standalone)
#
# The default run is ten gates behind the one baseline:
#   1. the static lint (MPT001-008, MPT012) + protocol model check
#      (MPT009-011);
#   2. an explicit `mcheck` pass, so the exhaustive state counts land in
#      the CI log even when everything is green;
#   3. smoke `conform` passes over the checked-in good-run journals —
#      the trace-conformance path exercised on every lint: the chaos
#      fixture covers TC201-203, the dynamics fixture carries
#      param_version records so TC204 runs non-vacuously;
#   4. live-snapshot schema validation over the checked-in golden
#      (tests/fixtures/live — the `obs live --validate` contract);
#   5. the training-dynamics gate over the checked-in dynamics golden
#      (tests/fixtures/dynamics/good_run vs scripts/dynamics_smoke.json
#      — the `obs dynamics --gate` contract);
#   6. a jax-free import probe of the shared quant kernels
#      (mpit_tpu/quant.py + the transport.wire re-exports) — the host
#      wire path must never grow a backend dependency;
#   7. the black-box post-mortem contract over the checked-in golden
#      (tests/fixtures/blackbox: 3-rank run, rank 2 SIGKILLed) — exit
#      codes pinned: the incident fixture must exit 1 naming rank 2 as
#      first-mover, an empty dir must exit 2;
#   8. the concurrency gate: each seeded MPT013/014/015 fixture must
#      trip exactly its rule through the real CLI (the lockset walk
#      can't silently lose thread-root discovery), and the RT103
#      vector-clock sanitizer must catch a seeded unsynchronized write
#      pair while staying silent on the lock-ordered twin;
#   9. the wire-schema gate: the inferred per-tag payload schemas must
#      match the checked-in wire-schema.lock.json (protocol changes are
#      declared with `schema --update-lock`, never discovered in prod);
#      each seeded MPT016/017/018 fixture must trip exactly its rule
#      through the real CLI; and the differential codec fuzz gate runs
#      10k seeded examples (roundtrip + framed-vs-pickle differential +
#      mutation corpus: every corrupted frame lands on WireDecodeError
#      or the original value — never a wrong value or a crash) plus a
#      replay of the checked-in corpus under tests/fixtures/wire_corpus;
#  10. the numerics gate: each seeded MPT020/021/022 fixture (code
#      accumulation, unpaired error feedback, mode/scale provenance
#      mismatch) must trip exactly its rule through the real CLI, and
#      the RT104 numerics sanitizer must catch a seeded NaN injection
#      and a zero-absmax row while staying silent on a clean
#      quantize→dequantize round.
# Every gate prints its wall-clock ([lint] gate N ... Xs); the whole
# default run is bounded to < 30 s with the wire-schema gate itself
# under 20 s (tests/test_lint_gate.py enforces both, and separately
# pins the in-process whole-package scan to < 5 s).
#
# Exit codes: 0 clean vs baseline, 1 new findings, 2 usage error.
# The linter parses, never imports, the scanned code and initializes no
# jax backend — safe for pre-commit hooks.
set -euo pipefail

cd "$(dirname "$0")/.."

STRICT=0
if [[ "${1:-}" == "--strict" ]]; then
    STRICT=1
    shift
fi

if [[ "${1:-}" == "--update" ]]; then
    shift
    exec python -m mpit_tpu.analysis --write-baseline "${@:-mpit_tpu/}"
fi

if [[ "${1:-}" == "--fix" ]]; then
    shift
    exec python -m mpit_tpu.analysis --fix "${@:-mpit_tpu/}"
fi

# per-gate wall-clock: every gate below reports its own cost, so a
# budget regression (the 15 s bound) names its gate instead of hiding
# in the total
_gate_last=$(date +%s%N)
gate_done() {
    local now
    now=$(date +%s%N)
    awk -v n="$1" -v a="$_gate_last" -v b="$now" \
        'BEGIN{printf "[lint] gate %-14s %6.2fs\n", n, (b-a)/1e9}'
    _gate_last=$now
}

python -m mpit_tpu.analysis "${@:-mpit_tpu/}"
gate_done lint

# explicit-path gates only make sense for the default whole-package run
if [[ $# -eq 0 ]]; then
    python -m mpit_tpu.analysis mcheck
    gate_done mcheck
    # one extraction, two audits: the chaos fixture covers TC201-203
    # under faults, the dynamics fixture carries param_version records
    # so TC204 (version monotonicity) runs non-vacuously
    python -m mpit_tpu.analysis conform \
        tests/fixtures/conformance/good_run tests/fixtures/dynamics/good_run
    gate_done conform
    # the live-snapshot schema contract, gated on the checked-in golden
    python -m mpit_tpu.obs live tests/fixtures/live --validate
    gate_done live
    # the update-quality contract, gated on the same dynamics golden
    python -m mpit_tpu.obs dynamics tests/fixtures/dynamics/good_run \
        --gate scripts/dynamics_smoke.json
    gate_done dynamics
    # the shared quant kernels must stay importable WITHOUT a jax
    # backend (the host wire path depends on it; the jnp half is lazy) —
    # and the transport re-exports the MPT007 coverage rides on must
    # resolve from the numpy side alone
    python - <<'EOF'
import importlib.util, sys
import numpy as np
sys.modules["jax"] = None  # poison: any jax import below fails loudly
spec = importlib.util.spec_from_file_location(
    "quant_probe", "mpit_tpu/quant.py"
)
quant = importlib.util.module_from_spec(spec)
sys.modules["quant_probe"] = quant  # dataclass machinery resolves via here
spec.loader.exec_module(quant)  # must not touch jax (the jnp half is lazy)
q = quant.quantize(np.ones(8, np.float32), "int8")
out = quant.dequantize(q)
assert out.shape == (8,) and out.dtype == np.float32
EOF
    gate_done quant-probe
    # the post-mortem contract, gated on the checked-in incident golden
    # (exit codes are part of the CLI contract: 1 = incident found,
    # 2 = no dumps; one python process drives obs_main for both runs).
    # The package __init__s are stubbed out: like gate 6, this doubles
    # as a probe that the post-mortem path stays stdlib-only — an
    # incident box must never need a jax backend to read the black box
    python - <<'EOF'
import importlib, io, json, os, sys, tempfile, types
from contextlib import redirect_stderr, redirect_stdout

for name, path in (("mpit_tpu", "mpit_tpu"), ("mpit_tpu.obs", "mpit_tpu/obs")):
    stub = types.ModuleType(name)
    stub.__path__ = [path]
    sys.modules[name] = stub
obs_main = importlib.import_module("mpit_tpu.obs.__main__").main

buf = io.StringIO()
with redirect_stdout(buf):
    rc = obs_main(["postmortem", "tests/fixtures/blackbox", "--json"])
assert rc == 1, f"postmortem gate: incident fixture exited {rc} (want 1)"
rep = json.loads(buf.getvalue())
assert rep["verdict"] == "incident", rep["verdict"]
assert rep["first_mover"]["rank"] == 2, rep["first_mover"]
assert "2" in rep["exchanges"], sorted(rep["exchanges"])
empty = tempfile.mkdtemp()
try:
    with redirect_stderr(io.StringIO()):
        rc = obs_main(["postmortem", empty, "--json"])
finally:
    os.rmdir(empty)
assert rc == 2, f"postmortem gate: empty dir exited {rc} (want 2)"
print(
    "postmortem gate: first-mover rank 2, "
    f"{len(rep['exchanges']['2']['pushes'])} reconstructed round(s), "
    "exit codes 1/2 pinned — ok"
)
EOF
    gate_done postmortem
    # gate 8: the concurrency contract. (a) Each seeded fixture must
    # trip exactly its rule through the REAL CLI — a regression in
    # thread-root discovery or the lockset walk turns these scans
    # silently green, so the expected exit-1 is asserted, not assumed.
    for rule in MPT013 MPT014 MPT015; do
        low=$(echo "$rule" | tr '[:upper:]' '[:lower:]')
        if python -m mpit_tpu.analysis --no-baseline --only "$rule" \
                "tests/fixtures/analysis/fixture_${low}" > /dev/null; then
            echo "concurrency gate: fixture_${low} no longer trips ${rule}" >&2
            exit 1
        fi
    done
    # (b) RT103 smoke: the vector-clock sanitizer must flag a seeded
    # unsynchronized write pair (with both stacks) and stay silent when
    # the same traffic is ordered through a tracked lock
    python - <<'EOF'
import threading
from mpit_tpu.analysis import runtime as rt

with rt.checking(race=True) as ck:
    def bump():
        for _ in range(3):
            rt.note("gate.shared", True)
    ts = [threading.Thread(target=bump) for _ in range(2)]
    [t.start() for t in ts]; [t.join() for t in ts]
races = [f for f in ck.findings if f.rule == "RT103"]
assert races, "RT103 smoke: seeded race not caught"
assert races[0].message.count('File "') >= 2, "RT103 smoke: missing a stack"

with rt.checking(race=True) as ck2:
    lk = rt.make_lock("gate.lk")
    def bump2():
        for _ in range(3):
            with lk:
                rt.note("gate.shared2", True)
    ts = [threading.Thread(target=bump2) for _ in range(2)]
    [t.start() for t in ts]; [t.join() for t in ts]
assert not [f for f in ck2.findings if f.rule == "RT103"], \
    "RT103 smoke: false positive on lock-ordered writes"
print("concurrency gate: 3 fixtures trip their rules, RT103 smoke ok")
EOF
    gate_done concurrency
    # gate 9: the wire-schema contract. (a) The inferred per-tag payload
    # schemas must match the checked-in lockfile — a protocol change
    # ships only together with its declared schema bump.
    python -m mpit_tpu.analysis schema --check
    # (b) each seeded schema fixture must trip exactly its rule through
    # the REAL CLI (same contract as gate 8: expected exit-1 asserted)
    for rule in MPT016 MPT017 MPT018; do
        low=$(echo "$rule" | tr '[:upper:]' '[:lower:]')
        fixture="tests/fixtures/analysis/fixture_${low}"
        [[ -d "$fixture" ]] || fixture="${fixture}.py"
        if python -m mpit_tpu.analysis --no-baseline --only "$rule" \
                "$fixture" > /dev/null; then
            echo "wire-schema gate: fixture_${low} no longer trips ${rule}" >&2
            exit 1
        fi
    done
    # (c) the differential codec fuzz gate: 10k seeded examples of
    # roundtrip + framed-vs-pickle equality + mutation outcomes, plus a
    # replay of the checked-in regression corpus — every corrupted frame
    # must land on WireDecodeError or the original value, never a wrong
    # value, a crash, or a hang
    python -m mpit_tpu.analysis fuzz --examples 10000 \
        --corpus tests/fixtures/wire_corpus/corpus.jsonl
    gate_done wire-schema
    # gate 10: the numerics contract. (a) Each seeded precision-flow
    # fixture must trip exactly its rule through the REAL CLI (same
    # expected-exit-1 discipline as gates 8/9 — a regression in the
    # dataflow walk must not turn these scans silently green).
    for rule in MPT020 MPT021 MPT022; do
        low=$(echo "$rule" | tr '[:upper:]' '[:lower:]')
        if python -m mpit_tpu.analysis --no-baseline --only "$rule" \
                "tests/fixtures/analysis/fixture_${low}.py" > /dev/null; then
            echo "numerics gate: fixture_${low} no longer trips ${rule}" >&2
            exit 1
        fi
    done
    # (b) RT104 smoke: the numerics sanitizer must stay silent on a
    # clean quantize→dequantize round (including a legitimate all-zero
    # row), catch a seeded NaN injection exactly once per site, and
    # catch a non-finite EF-residual norm
    python - <<'EOF'
import numpy as np
from mpit_tpu import quant
from mpit_tpu.analysis import runtime as rt

with rt.checking(numerics=True) as ck:
    clean = np.arange(12, dtype=np.float32).reshape(3, 4)
    clean[1] = 0.0  # zero-absmax row: legitimate, must not trip
    codes, scales = quant.quantize_rows(clean, "int8")
    quant.dequantize_rows(codes, scales, "int8")
    quant.dequantize(quant.quantize(clean.ravel(), "int8"))
assert not ck.findings, f"RT104 smoke: clean round tripped {ck.findings}"

with rt.checking(numerics=True) as ck2:
    poisoned = np.ones(8, np.float32)
    poisoned[3] = np.nan  # seeded NaN injection
    for _ in range(3):  # once-per-site dedup: 3 calls, 1 finding
        quant.quantize(poisoned, "int8")
    rt.note_residual_norm("gate.ef", float("nan"))
rules = [f.rule for f in ck2.findings]
assert rules == ["RT104", "RT104"], f"RT104 smoke: got {rules}"
assert 'File "' in ck2.findings[0].message, "RT104 smoke: missing stack"
print("numerics gate: 3 fixtures trip their rules, RT104 smoke ok")
EOF
    gate_done numerics
    # bench trajectory drift should be SEEN at lint time; it blocks only
    # under --strict (CI), because bench noise must never block a commit
    if [[ "$STRICT" == "1" ]]; then
        python scripts/bench_gate.py --strict --trend
    else
        python scripts/bench_gate.py --trend || true
    fi
    gate_done bench-trend
fi
