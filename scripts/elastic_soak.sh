#!/usr/bin/env bash
# Elastic-membership chaos soak: run a 3-rank process-mode EASGD job with
# the elastic supervisor armed, killing a random rank every few seconds
# and respawning it (clients re-enter via JOIN, a killed server restores
# from its shard snapshot), then gate the survivors' journals:
#
#   scripts/elastic_soak.sh [MAX_SECONDS] [KILL_SEED] [REPORT_DIR]
#
# - `obs dynamics --gate`: no divergence, bounded staleness;
# - a versions-monotonic check over the (gen, version) order — a restored
#   server stepping its center version backwards within a generation is
#   exactly the double-apply/lost-snapshot failure the shard checkpoint
#   exists to prevent;
# - `analysis conform`: TC201-TC204 over the run's journals with
#   membership.jsonl licensing the churned ranks' truncated tails;
# - at least one kill must actually have landed (a soak that never
#   churned proved nothing — fail loudly rather than pass vacuously);
# - `obs postmortem`: the black-box dumps the kills triggered must
#   assemble into a cross-rank report naming a killed rank as
#   first-mover with reconstructed final exchange rounds. The report
#   (human + JSON + the raw dumps) is ARCHIVED to REPORT_DIR (default
#   ./soak_reports/<timestamp>) — the working dirs are temp-dirs wiped
#   on exit, and a soak that discards its own forensics is pointless.
#
# The kill schedule is seeded (MPIT_ELASTIC_KILL_SEED) so a failure
# replays: rerun with the same seed and the same victims die at the same
# cadence. Wall-clock is bounded by MAX_SECONDS (default 180) via
# timeout(1); the killer only picks victims that still have respawn
# budget, so the supervisor cannot run the world out of respawns itself.
set -euo pipefail
cd "$(dirname "$0")/.."

MAX_SECONDS="${1:-180}"
KILL_SEED="${2:-1234}"
REPORT_DIR="${3:-soak_reports/$(date +%Y%m%d-%H%M%S)}"
OUT="$(mktemp -d)"
CKPT="$(mktemp -d)"
OUT2="$(mktemp -d)"
CKPT2="$(mktemp -d)"
trap 'rm -rf "$OUT" "$CKPT" "$OUT2" "$CKPT2"' EXIT

GATE="$OUT/dynamics_gate.json"
printf '{"staleness_p99_max": 256, "allow_diverging": false}\n' > "$GATE"

echo "=== elastic soak: 3-rank churn run (seed ${KILL_SEED}, budget ${MAX_SECONDS}s) ===" >&2
env JAX_PLATFORMS=cpu \
    MPIT_OBS_DIR="$OUT" \
    MPIT_ELASTIC_RESPAWN=1 \
    MPIT_ELASTIC_CKPT_DIR="$CKPT" \
    MPIT_ELASTIC_CKPT_EVERY=3 \
    MPIT_ELASTIC_KILL_EVERY_S=3 \
    MPIT_ELASTIC_KILL_SEED="$KILL_SEED" \
    MPIT_ELASTIC_MAX_RESPAWNS=4 \
    timeout -k 10 "$MAX_SECONDS" \
    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
    --model mlp --steps 48 --train-size 256 --algo ps-easgd

echo "=== elastic soak: dynamics gate ===" >&2
python -m mpit_tpu.obs dynamics "$OUT" --gate "$GATE" --json \
    > "$OUT/dynamics.json"
python - "$OUT" <<'EOF'
import json, sys
out = sys.argv[1]
report = json.load(open(f"{out}/dynamics.json"))
run = report["run"]
if run["versions_monotonic"] is False:
    sys.exit("elastic_soak: center version stepped backwards within a "
             "generation — snapshot restore lost state")
members = [json.loads(line)
           for line in open(f"{out}/membership.jsonl")]
kills = [m for m in members if m.get("kind") == "kill"]
respawns = [m for m in members if m.get("kind") == "respawn"]
if not kills:
    sys.exit("elastic_soak: no rank was ever killed — the soak proved "
             "nothing (machine too fast? raise --steps)")
if not respawns:
    sys.exit("elastic_soak: kills landed but nothing respawned — the "
             "supervisor is not replacing ranks")
restores = sum(s.get("restores", 0) for s in report["servers"].values())
print(f"elastic_soak: {len(kills)} kill(s), {len(respawns)} respawn(s), "
      f"{restores} server restore(s), versions monotonic, gate green")
EOF

echo "=== elastic soak: conformance replay ===" >&2
python -m mpit_tpu.analysis conform "$OUT"

echo "=== elastic soak: cross-rank post-mortem ===" >&2
# the kills above asked every survivor's flight recorder to dump; the
# post-mortem must now assemble those windows into a non-empty incident
# report naming a killed rank as first-mover (exit 1 = incident found,
# which for a chaos soak is the EXPECTED outcome)
rc=0
python -m mpit_tpu.obs postmortem "$OUT" --json \
    > "$OUT/postmortem.json" || rc=$?
if [[ $rc -ne 1 ]]; then
    echo "elastic_soak: postmortem exited $rc (want 1 = incident):" \
         "kills landed but no cross-rank incident was assembled" >&2
    exit 1
fi
rc=0
python -m mpit_tpu.obs postmortem "$OUT" > "$OUT/postmortem.txt" || rc=$?
python - "$OUT" <<'EOF'
import json, sys
out = sys.argv[1]
rep = json.load(open(f"{out}/postmortem.json"))
members = [json.loads(line) for line in open(f"{out}/membership.jsonl")]
killed = {m["rank"] for m in members if m.get("kind") == "kill"}
mover = rep["first_mover"].get("rank")
if mover not in killed:
    sys.exit(f"elastic_soak: postmortem named rank {mover} as "
             f"first-mover but the killer's victims were {sorted(killed)}")
rounds = sum(len(e["pushes"]) for e in rep["exchanges"].values())
if rounds == 0:
    sys.exit("elastic_soak: postmortem reconstructed no exchange rounds "
             "— the dump windows are empty")
print(f"elastic_soak: postmortem names rank {mover} (killed) as "
      f"first-mover, {rounds} exchange round(s) reconstructed across "
      f"{len(rep['ranks'])} dumped window(s)")
EOF

# ---------------------------------------------------------------------------
# Leg 2 — sharded server kill (docs/ROBUSTNESS.md "Shard ownership &
# resharding"): 2 servers × 2 clients with MPIT_PS_SHARDS ring placement,
# and the killer aimed ONLY at server rank 0. A server dying must be a
# reshard, not an outage: clients declare it dead within seconds
# (MPIT_PS_TIMEOUT), reroute its shards to the survivor (journaled as
# reshard_repair with moved > 0), and finish training with zero skipped
# rounds of lost coverage. Gates: the run exits 0, the reshard actually
# happened, the dynamics gate stays green, and the post-mortem names the
# killed SERVER as first-mover.
echo "=== elastic soak: sharded server-kill leg (seed ${KILL_SEED}) ===" >&2
env JAX_PLATFORMS=cpu \
    MPIT_OBS_DIR="$OUT2" \
    MPIT_ELASTIC_RESPAWN=1 \
    MPIT_ELASTIC_CKPT_DIR="$CKPT2" \
    MPIT_ELASTIC_CKPT_EVERY=2 \
    MPIT_ELASTIC_KILL_EVERY_S=20 \
    MPIT_ELASTIC_KILL_RANKS=0 \
    MPIT_ELASTIC_KILL_SEED="$KILL_SEED" \
    MPIT_ELASTIC_MAX_RESPAWNS=2 \
    MPIT_ELASTIC_RESPAWN_DELAY_S=8 \
    MPIT_PS_SHARDS=4 \
    MPIT_PS_TIMEOUT=3 \
    MPIT_PS_MAX_RETRIES=0 \
    MPIT_CONNECT_RETRY_S=2 \
    timeout -k 10 "$MAX_SECONDS" \
    python -m mpit_tpu.launch -n 4 examples/ptest_proc.py \
    --model mlp --steps 1600 --train-size 256 --algo ps-easgd --servers 2

echo "=== elastic soak: sharded leg gates ===" >&2
python -m mpit_tpu.obs dynamics "$OUT2" --gate "$GATE" --json \
    > "$OUT2/dynamics.json"
rc=0
python -m mpit_tpu.obs postmortem "$OUT2" --json \
    > "$OUT2/postmortem.json" || rc=$?
if [[ $rc -ne 1 ]]; then
    echo "elastic_soak: sharded-leg postmortem exited $rc (want 1):" \
         "the server kill left no cross-rank incident" >&2
    exit 1
fi
python - "$OUT2" <<'EOF'
import glob, json, sys
out = sys.argv[1]
members = [json.loads(line) for line in open(f"{out}/membership.jsonl")]
kills = [m for m in members if m.get("kind") == "kill"]
if not kills:
    sys.exit("elastic_soak: sharded leg never killed the server")
if any(m["rank"] != 0 for m in kills):
    sys.exit(f"elastic_soak: kill targeting broken — victims "
             f"{sorted({m['rank'] for m in kills})}, want only rank 0")
repairs = []
for path in glob.glob(f"{out}/obs_rank*.jsonl"):
    for line in open(path):
        rec = json.loads(line)
        if rec.get("ev") == "reshard_repair":
            repairs.append(rec)
moved = sum(r.get("moved", 0) for r in repairs)
if not repairs or moved == 0:
    sys.exit("elastic_soak: server killed but no reshard_repair was "
             "journaled — clients skipped the round instead of "
             "rerouting the dead server's shards")
if any(r.get("dead") != 0 for r in repairs):
    sys.exit(f"elastic_soak: repair named the wrong dead rank: {repairs}")
rep = json.load(open(f"{out}/postmortem.json"))
mover = rep["first_mover"].get("rank")
if mover != 0:
    sys.exit(f"elastic_soak: postmortem named rank {mover} as "
             "first-mover, want the killed server (rank 0)")
run = json.load(open(f"{out}/dynamics.json"))["run"]
if run["versions_monotonic"] is False:
    sys.exit("elastic_soak: sharded leg stepped a center version "
             "backwards within a generation")
print(f"elastic_soak: sharded leg — {len(kills)} server kill(s), "
      f"{moved} shard(s) rerouted across {len(repairs)} repair(s), "
      "postmortem blames the server, gate green")
EOF

# archive the evidence before the EXIT trap wipes the working dirs
mkdir -p "$REPORT_DIR"
cp "$OUT/postmortem.json" "$OUT/postmortem.txt" "$REPORT_DIR/"
cp "$OUT/membership.jsonl" "$REPORT_DIR/" 2>/dev/null || true
cp -r "$OUT/blackbox" "$REPORT_DIR/blackbox" 2>/dev/null || true
cp "$OUT2/postmortem.json" "$REPORT_DIR/postmortem_sharded.json" 2>/dev/null || true
cp "$OUT2/membership.jsonl" "$REPORT_DIR/membership_sharded.jsonl" 2>/dev/null || true
echo "elastic_soak: post-mortem archived to $REPORT_DIR" >&2
echo "elastic_soak: OK"
