#!/usr/bin/env bash
# One-command Perfetto timeline demo (docs/OBSERVABILITY.md):
#
#   scripts/trace_demo.sh [OUT_DIR] [MAX_SECONDS]
#
# Runs a tiny 3-rank process-mode PS training (1 server, 2 clients over
# SocketTransport) with obs tracing armed and mild chaos drops so the
# fault overlay has something to show, then merges the per-rank journals:
#
#   OUT_DIR/obs_rank{0,1,2}.jsonl   per-rank event journals
#   OUT_DIR/trace.json              open in https://ui.perfetto.dev
#
# Wall-clock is bounded: the training run is killed at MAX_SECONDS
# (default 120) rather than hanging the shell.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT_DIR="${1:-/tmp/mpit_trace_demo}"
MAX_SECONDS="${2:-120}"

rm -rf "$OUT_DIR"
mkdir -p "$OUT_DIR"

echo "=== trace_demo: 3-rank easgd run, journals -> $OUT_DIR ==="
env JAX_PLATFORMS=cpu \
    MPIT_OBS_DIR="$OUT_DIR" \
    MPIT_CHAOS_SEED=7 MPIT_CHAOS_DROP=0.03 MPIT_CHAOS_TAGS=1,4 \
    timeout -k 10 "$MAX_SECONDS" \
    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
    --model mlp --steps 12 --train-size 256 --algo ps-easgd

echo "=== trace_demo: merging journals ==="
python -m mpit_tpu.obs merge "$OUT_DIR" -o "$OUT_DIR/trace.json"
python -m mpit_tpu.obs summary "$OUT_DIR"

echo "trace_demo: OK — open $OUT_DIR/trace.json in https://ui.perfetto.dev"
