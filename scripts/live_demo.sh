#!/usr/bin/env bash
# One-command live-telemetry demo (docs/OBSERVABILITY.md, *Live
# telemetry plane*):
#
#   scripts/live_demo.sh [OUT_DIR] [MAX_SECONDS]
#
# Runs a small multi-process PS training (1 server, 2 clients over real
# SocketTransport) with the live plane armed, then reads the per-rank
# snapshots back two ways:
#
#   OUT_DIR/live/rank_{0,1,2}.json  atomic per-rank snapshots
#   stdout                          dashboard table, then --once --json
#
# Wall-clock is bounded: the training run is killed at MAX_SECONDS
# (default 120) rather than hanging the shell. The final --once pass
# runs the alert engine; new alerts exit 1 and fail the demo — a clean
# 3-rank run must be alert-free.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT_DIR="${1:-/tmp/mpit_live_demo}"
MAX_SECONDS="${2:-120}"

rm -rf "$OUT_DIR"
mkdir -p "$OUT_DIR"

echo "=== live_demo: 3-rank easgd run, snapshots -> $OUT_DIR/live ==="
env JAX_PLATFORMS=cpu \
    MPIT_OBS_DIR="$OUT_DIR" \
    MPIT_OBS_LIVE=1 \
    MPIT_OBS_LIVE_INTERVAL=0.25 \
    timeout -k 10 "$MAX_SECONDS" \
    python -m mpit_tpu.launch -n 3 examples/ptest_proc.py \
    --model mlp --steps 16 --train-size 256 --algo ps-easgd

echo "=== live_demo: dashboard (one pass) ==="
python -m mpit_tpu.obs live "$OUT_DIR" --once --no-alerts

echo "=== live_demo: machine-readable + alert gate ==="
python -m mpit_tpu.obs live "$OUT_DIR" --once --json

echo "live_demo: OK — watch a run in-flight with: python -m mpit_tpu.obs live $OUT_DIR"
